"""Paged KV cache + host-DRAM overflow tier (ISSUE 16) — engine-level
parity and behavior tests.

The contract under test: routing every prefix mechanism through the
radix/paged pool — including spilling retained prefixes to host DRAM and
swapping them back on a hit — changes NOTHING about the emitted token
streams.  Tokens AND logprobs must be bit-identical to an engine that
never evicts, across greedy and sampled decoding, including a host-swap
round trip of a mid-generation (interrupted) prefix.
"""

import os

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module", autouse=True)
def _debug_locks():
    old = os.environ.get("AREAL_DEBUG_LOCKS")
    os.environ["AREAL_DEBUG_LOCKS"] = "1"
    yield
    if old is None:
        os.environ.pop("AREAL_DEBUG_LOCKS", None)
    else:
        os.environ["AREAL_DEBUG_LOCKS"] = old


@pytest.fixture(scope="module")
def setup(_debug_locks):
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=2, max_seq_len=128, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _greedy_reference(cfg, params, prompt, n_new):
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        ids = np.asarray(seq, np.int32)[None]
        pos = np.arange(len(seq), dtype=np.int32)[None]
        seg = np.zeros((1, len(seq)), np.int32)
        logits = np.asarray(forward(params, cfg, ids, pos, seg))[0, -1]
        tok = int(np.argmax(logits))
        out.append(tok)
        seq.append(tok)
    return out


def _run_workload(eng, reqs):
    """Submit request batches sequentially; returns the finished requests."""
    done = []
    for batch in reqs:
        rs = [
            GenRequest(rid=r["rid"], input_ids=list(r["ids"]),
                       max_new_tokens=r["n"],
                       temperature=r.get("temp", 0.0))
            for r in batch
        ]
        eng.generate_blocking(rs)
        done.extend(rs)
    return done


def _fillers(rng, count, n=4, length=20):
    return [
        {"rid": f"fill-{i}", "ids": rng.integers(0, 97, length).tolist(),
         "n": n}
        for i in range(count)
    ]


def test_host_swap_round_trip_is_bit_identical(setup):
    """A retained prefix forced through host DRAM (spill on slot pressure,
    swap back on a radix hit) must leave the multi-turn continuation
    bit-identical — tokens and logprobs — to an engine with enough slots
    to keep it device-resident."""
    cfg, params = setup
    rng = np.random.default_rng(21)
    turn1 = rng.integers(0, 97, 24).tolist()
    fills = _fillers(np.random.default_rng(22), 2)

    def workload(transcript_holder):
        # [turn1] -> [2 fillers overwrite both slots] -> [turn2]
        yield [{"rid": "t1", "ids": turn1, "n": 6}]
        yield fills
        yield [{"rid": "t2", "ids": transcript_holder[0], "n": 6}]

    # reference: 4 slots, no host tier — turn1's prefix stays on device
    ref_eng = _engine(cfg, params, n_slots=4)
    r1 = GenRequest(rid="t1", input_ids=list(turn1), max_new_tokens=6,
                    temperature=0.0)
    ref_eng.generate_blocking([r1])
    transcript = turn1 + r1.output_tokens + rng.integers(0, 97, 4).tolist()
    ref_done = _run_workload(ref_eng, [fills,
                                       [{"rid": "t2", "ids": transcript,
                                         "n": 6}]])
    ref_t2 = ref_done[-1]
    assert ref_eng.stats["prefix_cache_host_swaps"] == 0

    # paged: 2 slots + host tier — the fillers evict turn1's prefix to
    # host DRAM; turn2's radix hit swaps it back in
    eng = _engine(cfg, params, n_slots=2, host_offload=True,
                  host_cache_mb=8, host_min_tokens=8)
    h1 = GenRequest(rid="t1", input_ids=list(turn1), max_new_tokens=6,
                    temperature=0.0)
    eng.generate_blocking([h1])
    assert h1.output_tokens == r1.output_tokens
    done = _run_workload(eng, [fills, [{"rid": "t2", "ids": transcript,
                                        "n": 6}]])
    t2 = done[-1]

    assert eng.stats["prefix_cache_host_swaps"] >= 2  # spill + swap-in
    assert eng.stats["suffix_calls"] >= 1  # warm start, not a cold prefill
    assert t2.output_tokens == ref_t2.output_tokens
    assert t2.output_logprobs == ref_t2.output_logprobs
    assert t2.cache_hit_tokens >= len(turn1)
    eng.pool.check_page_table()


def test_host_swap_mid_generation_interrupt_resume(setup):
    """The acceptance case: an INTERRUPTED generation's accumulated prefix
    survives a full spill/swap-in round trip and resumes to exactly the
    uninterrupted greedy rollout."""
    cfg, params = setup
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 97, 16).tolist()
    eng = _engine(cfg, params, n_slots=2, host_offload=True,
                  host_cache_mb=8, host_min_tokens=8)

    r1 = GenRequest(rid="i", input_ids=list(prompt), max_new_tokens=10,
                    temperature=0.0)
    eng.submit(r1)
    while len(r1.output_tokens) < 3:
        eng.step(chunk=2)
    eng.abort_all("abort")  # mid-generation: prefix retained in-slot
    got = len(r1.output_tokens)
    assert got >= 3 and r1.stop_reason == "abort"

    # slot pressure pushes the interrupted prefix through host DRAM
    _run_workload(eng, [_fillers(np.random.default_rng(24), 2)])
    assert eng.stats["prefix_cache_host_swaps"] >= 1

    resumed = GenRequest(rid="i", input_ids=prompt + r1.output_tokens,
                         max_new_tokens=10 - got, temperature=0.0)
    eng.generate_blocking([resumed])
    assert eng.stats["prefix_cache_host_swaps"] >= 2  # ...and back in
    ref = _greedy_reference(cfg, params, prompt, 10)
    assert r1.output_tokens + resumed.output_tokens == ref


def test_sampled_streams_invariant_to_host_tier(setup):
    """Counter-keyed sampling: the SAME workload, sampled at temperature
    1.0, must emit identical streams whether prefixes ride device
    residency or a host round trip — stream keys depend on (stream_id,
    position), never on cache placement."""
    cfg, params = setup
    rng = np.random.default_rng(25)
    turn1 = rng.integers(0, 97, 24).tolist()
    fills = _fillers(np.random.default_rng(26), 2)

    outs = []
    for kw in (
        dict(n_slots=4),
        dict(n_slots=2, host_offload=True, host_cache_mb=8,
             host_min_tokens=8),
    ):
        eng = _engine(cfg, params, **kw)
        r1 = GenRequest(rid="s1", input_ids=list(turn1), max_new_tokens=6,
                        temperature=1.0, top_p=0.9)
        eng.generate_blocking([r1])
        transcript = turn1 + r1.output_tokens
        done = _run_workload(eng, [fills, [{"rid": "s2",
                                            "ids": transcript, "n": 6,
                                            "temp": 1.0}]])
        outs.append((r1, done[-1], eng))
    (a1, a2, ref_eng), (b1, b2, host_eng) = outs
    assert host_eng.stats["prefix_cache_host_swaps"] >= 2
    assert ref_eng.stats["prefix_cache_host_swaps"] == 0
    assert a1.output_tokens == b1.output_tokens
    assert a2.output_tokens == b2.output_tokens
    assert a2.output_logprobs == b2.output_logprobs


def test_host_swap_mints_no_new_decode_programs(setup):
    """Static-shape discipline: spill/swap-in traffic may compile its own
    bucketed gather/scatter programs, but the decode program family must
    not grow — a swapped-in row is read through the same page table as
    any other."""
    cfg, params = setup
    rng = np.random.default_rng(27)
    eng = _engine(cfg, params, n_slots=2, host_offload=True,
                  host_cache_mb=8, host_min_tokens=8)
    warm = rng.integers(0, 97, 24).tolist()
    # n=12 walks the decode frontier across the 32- AND 64-column key
    # windows, then ONE full evict/swap-in cycle warms the swap-in aval
    # family (scatter-output cache) — the same one-time warmup the tiered
    # soaks grant cold device_put arrays.  Steady state starts here.
    _run_workload(eng, [[{"rid": "w", "ids": warm, "n": 12}]])
    _run_workload(eng, [_fillers(np.random.default_rng(30), 2)])
    _run_workload(eng, [[{"rid": "w0", "ids": warm + [1, 2, 3], "n": 4}]])
    assert eng.stats["prefix_cache_host_swaps"] >= 2
    baseline = eng._decode_fn._cache_size()
    for i in range(1, 4):  # repeated evict/swap-in churn
        _run_workload(eng, [_fillers(np.random.default_rng(30 + i), 2)])
        _run_workload(
            eng, [[{"rid": f"w{i}", "ids": warm + [1, 2, 3], "n": 4}]]
        )
    assert eng.stats["prefix_cache_host_swaps"] >= 6
    assert eng._decode_fn._cache_size() == baseline
    # ...and the whole family stays within the C6 decode budget
    # (tiers * ladder(16, 128) = 4 programs at this config)
    assert eng._decode_fn._cache_size() <= 4
    # the host transfer programs themselves stay on the bucket ladder
    assert eng._host_gather_fn._cache_size() <= len(
        {16, 32, 64, 128}
    )


def test_cold_start_swap_in_mints_nothing(setup):
    """ISSUE 17 satellite (the PR 16 cold-start caveat): engine init now
    pre-compiles the whole gather/scatter bucket ladder AND leaves the
    cache scatter-produced (out_shardings pins its aval), so the soak
    starts COLD — no warm evict/swap-in round granted — and the first
    real spill/swap-in/handoff cycle must mint zero programs anywhere."""
    cfg, params = setup
    rng = np.random.default_rng(33)
    eng = _engine(cfg, params, n_slots=2, host_offload=True,
                  host_cache_mb=8, host_min_tokens=8)
    g0 = eng._host_gather_fn._cache_size()
    s0 = eng._host_scatter_fn._cache_size()
    assert g0 == s0 == len({16, 32, 64, 128})  # full ladder, compiled cold
    warm = rng.integers(0, 97, 24).tolist()
    _run_workload(eng, [[{"rid": "w", "ids": warm, "n": 12}]])
    assert eng.stats["prefix_cache_host_swaps"] == 0  # still cold
    baseline = eng._decode_fn._cache_size()
    for i in range(3):  # evict/swap-in churn starts HERE, from cold
        _run_workload(eng, [_fillers(np.random.default_rng(34 + i), 2)])
        _run_workload(
            eng, [[{"rid": f"w{i}", "ids": warm + [1, 2, 3], "n": 4}]]
        )
    assert eng.stats["prefix_cache_host_swaps"] >= 4
    assert eng._decode_fn._cache_size() == baseline
    assert eng._host_gather_fn._cache_size() == g0
    assert eng._host_scatter_fn._cache_size() == s0


def test_prefix_cache_stats_accounting(setup):
    """hits/misses/evictions line up with the admission composition, and
    the hit-rate helper reflects them."""
    cfg, params = setup
    eng = _engine(cfg, params, n_slots=4)
    rng = np.random.default_rng(28)
    prompt = rng.integers(0, 97, 20).tolist()
    _run_workload(eng, [[{"rid": "a", "ids": prompt, "n": 4}]])
    assert eng.stats["prefix_cache_misses"] == 1
    assert eng.stats["prefix_cache_hits"] == 0
    assert eng.prefix_cache_hit_rate() == 0.0
    # multi-turn continuation: a device radix hit
    done = _run_workload(eng, [[{"rid": "a2",
                                 "ids": prompt + [5, 6, 7, 8, 9], "n": 4}]])
    assert eng.stats["prefix_cache_hits"] == 1
    assert eng.prefix_cache_hit_rate() == 0.5
    assert done[0].cache_hit_tokens >= len(prompt) - 1
    # an unrelated prompt overwriting a retained slot is an eviction
    before = eng.stats["prefix_cache_evictions"]
    _run_workload(eng, [_fillers(np.random.default_rng(29), 4)])
    assert eng.stats["prefix_cache_evictions"] >= before + 1


def test_migration_keeps_page_table_permutation(setup):
    """Tier migration is a page-table remap: after a tiered run with
    migrations the table must still be a permutation (no aliased or
    leaked cache rows) and retained prefixes must still match."""
    cfg, params = setup
    eng = _engine(cfg, params, n_slots=4, max_seq_len=128,
                  decode_tiers=2)
    rng = np.random.default_rng(31)
    reqs = [
        {"rid": f"m{i}", "ids": rng.integers(0, 97, 6).tolist(), "n": 40}
        for i in range(4)
    ]
    _run_workload(eng, [reqs])
    eng.pool.check_page_table()
    # at least one retained prefix is findable through the radix
    assert any(
        eng.pool.device_tokens(s) is not None for s in range(eng.n_slots)
    )
