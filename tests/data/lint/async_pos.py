"""C3 positive fixture: blocking calls on the event loop.

Expected findings: one async-blocking per marked line.
"""

import time

import requests


async def handler(request):
    time.sleep(0.1)  # VIOLATION: stalls every request on the loop
    body = requests.get("http://backend/health")  # VIOLATION: sync HTTP
    with open("/tmp/state.json") as f:  # VIOLATION: blocking file I/O
        data = f.read()
    return body, data


class Service:
    async def flush(self):
        self.path.write_text("done")  # VIOLATION: blocking file I/O
