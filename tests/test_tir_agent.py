"""TIR agent: code blocks execute in the sandbox mid-rollout, tool output
tokens are injected untrained, and generation continues with results in
context (reference: examples/tir)."""

import asyncio

import numpy as np
import pytest

from areal_tpu.agent import TIRMathAgent, make_agent
from areal_tpu.agent.math_env import MathVerifyEnv
from areal_tpu.agent.tir_agent import find_first_block
from areal_tpu.api.config import GenerationHyperparameters


class _Tok:
    def encode(self, text, add_special_tokens=False):
        return [ord(c) % 256 for c in text]

    def decode(self, tokens):
        return "".join(chr(t) for t in tokens)

    def apply_chat_template(self, messages, **kw):
        return self.encode("".join(m["content"] for m in messages))


class _ScriptedEngine:
    def __init__(self, replies):
        self.replies = list(replies)
        self.calls = 0
        self.prompts = []

    async def agenerate(self, req):
        self.prompts.append(_Tok().decode(req.input_ids))
        text = self.replies[min(self.calls, len(self.replies) - 1)]
        self.calls += 1
        out = [ord(c) % 256 for c in text]

        class R:
            input_tokens = list(req.input_ids)
            output_tokens = out
            output_logprobs = [-0.2] * len(out)
            output_versions = [3] * len(out)
            input_len = len(req.input_ids)
            output_len = len(out)
            stop_reason = "stop"

        return R()


def test_find_first_block():
    code, end = find_first_block("think ```python\nprint(1)\n``` more")
    assert code == "print(1)\n"
    assert end == len("think ```python\nprint(1)\n```")
    assert find_first_block("no code here") == (None, None)


def _run(agent, engine, env, data):
    async def go():
        if env is not None:
            async with env:
                return await agent.collect_trajectory(engine, env, data)
        return await agent.collect_trajectory(engine, None, data)

    return asyncio.run(go())


def test_tool_loop_executes_and_injects_output():
    # turn 1 emits a code block (plus overshoot to be discarded);
    # turn 2 reads the tool result and answers
    replies = [
        "compute: ```python\nprint(6*7)\n``` I guess 41",
        " so the answer is \\boxed{42}",
    ]
    engine = _ScriptedEngine(replies)
    agent = TIRMathAgent(
        GenerationHyperparameters(max_new_tokens=512), tokenizer=_Tok()
    )
    env = MathVerifyEnv(answer="42")
    (traj,) = _run(agent, engine, env, {"messages": [{"role": "user", "content": "6*7?"}]})

    assert engine.calls == 2
    # the second prompt contains the tool's stdout, not the overshoot
    assert "```output\n42\n```" in engine.prompts[1]
    assert "I guess 41" not in engine.prompts[1]

    full = _Tok().decode(list(traj["input_ids"]))
    assert "\\boxed{42}" in full
    assert traj["rewards"] == 1.0

    # injected tool tokens are loss-masked and carry logprob 0
    text_after_prompt = full[len("6*7?"):]
    lm = traj["loss_mask"][len("6*7?"):]
    lp = traj["logprobs"][len("6*7?"):]
    out_start = text_after_prompt.index("```output")
    out_end = text_after_prompt.index("```\n", out_start + 10) + 4
    assert lm[out_start:out_end].sum() == 0
    assert np.abs(lp[out_start:out_end]).sum() == 0
    # sampled tokens are trained
    assert lm[:out_start].sum() > 0
    assert traj["versions"][0] == -1  # prompt tokens: no version


def test_no_code_block_single_shot():
    engine = _ScriptedEngine(["the answer is \\boxed{9}"])
    agent = TIRMathAgent(
        GenerationHyperparameters(max_new_tokens=64), tokenizer=_Tok()
    )
    env = MathVerifyEnv(answer="9")
    (traj,) = _run(agent, engine, env, {"messages": [{"role": "user", "content": "3*3?"}]})
    assert engine.calls == 1
    assert traj["rewards"] == 1.0
    assert traj["loss_mask"][len("3*3?"):].sum() == len("the answer is \\boxed{9}")


def test_tool_call_cap():
    # the model emits a code block every turn; the loop must stop at the cap
    engine = _ScriptedEngine(["```python\nprint(1)\n```"] * 10)
    agent = TIRMathAgent(
        GenerationHyperparameters(max_new_tokens=4096),
        tokenizer=_Tok(),
        max_tool_calls=2,
    )
    (traj,) = _run(agent, engine, None, {"messages": [{"role": "user", "content": "q"}]})
    assert engine.calls == 3  # 2 tool rounds + the final continuation
    full = _Tok().decode(list(traj["input_ids"]))
    assert full.count("```output") == 2


def test_sandbox_error_feeds_back():
    replies = [
        "```python\nraise ValueError('nope')\n```",
        "\\boxed{0}",
    ]
    engine = _ScriptedEngine(replies)
    agent = TIRMathAgent(
        GenerationHyperparameters(max_new_tokens=512), tokenizer=_Tok()
    )
    (traj,) = _run(agent, engine, None, {"messages": [{"role": "user", "content": "q"}]})
    # the error marker reached the model's second prompt
    assert "```output" in engine.prompts[1]
    assert "exit" in engine.prompts[1] or "error" in engine.prompts[1]


def test_registry():
    agent = make_agent(
        "tir-math",
        gconfig=GenerationHyperparameters(max_new_tokens=8),
        tokenizer=_Tok(),
    )
    assert isinstance(agent, TIRMathAgent)
