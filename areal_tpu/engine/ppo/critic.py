"""PPO critic — value model with a scalar head over the backbone.

Behavioral counterpart of the reference's `PPOCritic` / `FSDPPPOCritic`
(areal/engine/ppo/critic.py): compute_values + ppo_update with the clipped
value loss.  The value head is an extra `[D]` param dotted against the
final-norm hidden states (replacing the reference's
AutoModelForTokenClassification-style critic); per-token values flow through
the same row-packed train path as the actor.
"""

import functools
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from areal_tpu.api.config import PPOCriticConfig
from areal_tpu.api.io_struct import SaveLoadMeta
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.models.transformer import forward_hidden
from areal_tpu.ops.functional import ppo_critic_loss_fn
from areal_tpu.utils.data import split_padded_tensor_dict_into_mb_list


def _value_forward(params, cfg, input_ids, positions, segment_ids, mesh=None):
    hidden = forward_hidden(params, cfg, input_ids, positions, segment_ids, mesh=mesh)
    head = params["value_head"].astype(hidden.dtype)
    return jnp.einsum("btd,d->bt", hidden, head)


def _value_hook(values, mb):
    return values.astype(jnp.float32)


def critic_loss(values, mb, eps_clip_value):
    return ppo_critic_loss_fn(
        values.astype(jnp.float32),
        mb["values"],
        mb["returns"],
        mb["loss_mask"],
        eps_clip_value=eps_clip_value,
    )


class JaxPPOCritic(JaxTrainEngine):
    def __init__(self, config: PPOCriticConfig, model_config=None):
        super().__init__(config, model_config)
        self._model_fn = _value_forward

    def initialize(self, addr=None, ft_spec=None) -> None:
        # build the backbone without the optimizer, attach the value head,
        # then build the optimizer over the full (backbone + head) tree
        optimizer_cfg = self.config.optimizer
        self.config.optimizer = None
        try:
            super().initialize(addr, ft_spec)
        finally:
            self.config.optimizer = optimizer_cfg
        self.params.pop("lm_head", None)
        if "value_head" not in self.params:
            D = self.model_config.hidden_size
            head = np.zeros(D, dtype=self.config.param_dtype)
            head_path = (
                os.path.join(self.config.path, "value_head.npy")
                if self.config.path
                else ""
            )
            if head_path and os.path.exists(head_path):
                head = np.load(head_path).astype(self.config.param_dtype)
            self.params["value_head"] = jax.device_put(
                jnp.asarray(head),
                jax.sharding.NamedSharding(self.mesh, P("fsdp")),
            )
        if optimizer_cfg is not None:
            self._build_optimizer(ft_spec)

    def compute_values(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """Token-level values [B, L] (reference: critic.py compute_values)."""
        return self.forward(batch, post_hook=_value_hook)

    def ppo_update(self, batch: Dict[str, np.ndarray]) -> List[Dict[str, float]]:
        cfg: PPOCriticConfig = self.config
        keys = ["input_ids", "attention_mask", "loss_mask", "values", "returns"]
        view = {k: batch[k] for k in keys if k in batch}
        mbs = split_padded_tensor_dict_into_mb_list(view, n_mbs=cfg.ppo_n_minibatches)
        if not hasattr(self, "_loss_fn"):
            self._loss_fn = functools.partial(
                critic_loss, eps_clip_value=cfg.value_eps_clip
            )
        out = []
        for mb in mbs.mbs:
            st = self.train_batch(
                mb,
                self._loss_fn,
                loss_weight_fn=lambda b: float(np.sum(b["loss_mask"])),
            )
            out.append(st)
        return out

    def save(self, meta: SaveLoadMeta) -> None:
        # the critic has no lm_head; present a tied config to the HF writer
        # so the backbone serialises without one
        head = self.params.pop("value_head")
        mc = self.model_config
        self.model_config = mc.replace(tie_word_embeddings=True)
        try:
            super().save(meta)
        finally:
            self.model_config = mc
            self.params["value_head"] = head
        np.save(os.path.join(meta.path, "value_head.npy"), np.asarray(head))

    def load(self, meta: SaveLoadMeta) -> None:
        head = self.params.get("value_head")
        mc = self.model_config
        self.model_config = mc.replace(tie_word_embeddings=True)
        try:
            super().load(meta)
        finally:
            self.model_config = mc.replace(
                dtype=self.config.dtype,
                param_dtype=self.config.param_dtype,
                remat=self.config.gradient_checkpointing,
            )
        self.params.pop("lm_head", None)
        head_path = os.path.join(meta.path, "value_head.npy")
        if os.path.exists(head_path):
            self.params["value_head"] = jax.device_put(
                jnp.asarray(np.load(head_path).astype(self.config.param_dtype)),
                jax.sharding.NamedSharding(self.mesh, P("fsdp")),
            )
        elif head is not None:
            self.params["value_head"] = head
