"""Fixture package for the C4 dead-module checker."""
