"""remat_policy knob: "dots" (save matmul outputs) must agree numerically
with "full" recompute — it only changes the HBM/FLOPs trade."""

import jax
import numpy as np
import pytest

from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config


def test_dots_policy_matches_full():
    base = tiny_config(vocab_size=64, qkv_bias=True, dtype="float32",
                       param_dtype="float32")
    params = init_params(base, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    B, L = 2, 16
    ids = rng.integers(0, 64, (B, L)).astype(np.int32)
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.zeros((B, L), np.int32)

    def loss(cfg):
        def f(p):
            logits = forward(p, cfg, ids, pos, seg)
            return jax.nn.logsumexp(logits).sum() / (B * L)

        return jax.value_and_grad(f)(params)

    l_full, g_full = loss(base.replace(remat=True, remat_policy="full"))
    for policy in ("dots", "save_attn", "save_mlp"):
        l_p, g_p = loss(base.replace(remat=True, remat_policy=policy))
        np.testing.assert_allclose(float(l_full), float(l_p), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            g_full,
            g_p,
        )


def test_scan_unroll_matches_rolled():
    base = tiny_config(vocab_size=64, qkv_bias=True, dtype="float32",
                       param_dtype="float32", num_layers=4)
    params = init_params(base, jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    B, L = 2, 16
    ids = rng.integers(0, 64, (B, L)).astype(np.int32)
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.zeros((B, L), np.int32)
    outs = []
    for unroll in (1, 2, 4):
        cfg = base.replace(scan_unroll=unroll)
        outs.append(np.asarray(forward(params, cfg, ids, pos, seg)))
    # 3 does not divide 4 -> falls back to 1, LOUDLY (ISSUE 20: the silent
    # fallback used to hide misconfigured ladders)
    with pytest.warns(UserWarning, match="scan_unroll=3 does not divide"):
        outs.append(np.asarray(
            forward(params, base.replace(scan_unroll=3), ids, pos, seg)
        ))
    for o in outs[1:]:
        np.testing.assert_allclose(outs[0], o, rtol=1e-6)
