"""Native C++ dataplane: parity with the pure-Python reference paths
(the reference tests its csrc kernels the same way —
realhf/tests/cpp_extensions/test_interval_ops.py vs torch reference)."""

import numpy as np
import pytest

from areal_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="g++ unavailable; native fallback covered elsewhere"
)


def _python_ffd(sizes, capacity):
    order = np.argsort(-np.asarray(sizes), kind="stable")
    bins, loads = [], []
    bin_of = np.empty(len(sizes), np.int32)
    for idx in order:
        size = int(sizes[idx])
        placed = False
        for b in range(len(bins)):
            if loads[b] + size <= capacity:
                loads[b] += size
                bin_of[idx] = b
                placed = True
                break
        if not placed:
            bin_of[idx] = len(bins)
            bins.append([idx])
            loads.append(size)
    return bin_of


def test_ffd_parity_random():
    rng = np.random.default_rng(0)
    for trial in range(20):
        n = int(rng.integers(1, 200))
        sizes = rng.integers(1, 512, n)
        capacity = int(rng.integers(64, 2048))
        got = native.ffd_assign(sizes, capacity)
        np.testing.assert_array_equal(got, _python_ffd(sizes, capacity))


def test_ffd_oversize_items_get_singletons():
    out = native.ffd_assign([10, 500, 20], capacity=100)
    # 500 exceeds capacity: own bin; 10+20 share the next
    assert out[1] != out[0] and out[0] == out[2]


def test_lpt_parity_random():
    rng = np.random.default_rng(1)
    for trial in range(20):
        n = int(rng.integers(1, 200))
        k = int(rng.integers(1, 8))
        sizes = rng.integers(1, 512, n)
        got = native.lpt_assign(sizes, k)
        loads = np.zeros(k, np.int64)
        expect = np.empty(n, np.int32)
        for idx in np.argsort(-sizes, kind="stable"):
            b = int(np.argmin(loads))
            expect[idx] = b
            loads[b] += int(sizes[idx])
        np.testing.assert_array_equal(got, expect)


def test_datapack_dispatch_matches_python_semantics(monkeypatch):
    from areal_tpu.utils import datapack

    sizes = list(np.random.default_rng(2).integers(1, 100, 64))
    with_native = datapack.ffd_allocate(sizes, capacity=256, min_groups=3)
    part_native = datapack.balanced_partition(sizes, 4)

    monkeypatch.setattr(native, "ffd_assign", lambda *a, **k: None)
    monkeypatch.setattr(native, "lpt_assign", lambda *a, **k: None)
    assert datapack.ffd_allocate(sizes, capacity=256, min_groups=3) == with_native
    assert datapack.balanced_partition(sizes, 4) == part_native
