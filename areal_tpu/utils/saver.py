"""Frequency-controlled HF-format checkpoint saving
(reference: areal/utils/saver.py `Saver`).

Saves are staged + renamed (ISSUE 15): a crash mid-save leaves a
``.tmp-*`` sibling, never a half-written checkpoint at the published
path a later run (or a human) would trust."""

import os
import shutil
from typing import Optional

from areal_tpu.api.config import SaverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.utils import logging
from areal_tpu.utils.timer import FrequencyControl

logger = logging.getLogger("saver")


class Saver:
    def __init__(self, config: SaverConfig, ft_spec=None, for_recover: bool = False):
        self.config = config
        self.ft_spec = ft_spec
        self.for_recover = for_recover
        self.freq = FrequencyControl(config)

    def save_root(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "recover_checkpoints" if self.for_recover else "checkpoints",
        )

    def save_path(self, step_info: StepInfo, name: str = "default") -> str:
        return os.path.join(
            self.save_root(),
            name,
            f"epoch{step_info.epoch}epochstep{step_info.epoch_step}"
            f"globalstep{step_info.global_step}",
        )

    def save(
        self,
        engine,
        epoch: int,
        epoch_step: int,
        global_step: int,
        name: str = "default",
        force: bool = False,
        with_optim: Optional[bool] = None,
        tokenizer=None,
    ) -> Optional[str]:
        """Save if the frequency budget elapsed; returns the path if saved."""
        if not self.freq.check(epoch, global_step, force=force):
            return None
        step_info = StepInfo(
            epoch=epoch, epoch_step=epoch_step, global_step=global_step,
            steps_per_epoch=self.ft_spec.steps_per_epoch if self.ft_spec else 0,
        )
        path = self.save_path(step_info, name)
        staging = os.path.join(
            os.path.dirname(path), f".tmp-{os.path.basename(path)}"
        )
        for stale in (staging, path):
            if os.path.isdir(stale):
                shutil.rmtree(stale)
        os.makedirs(staging, exist_ok=True)
        engine.save(SaveLoadMeta(
            path=staging,
            with_optim=self.for_recover if with_optim is None else with_optim,
            tokenizer=tokenizer,
        ))
        os.rename(staging, path)  # atomic publish on one filesystem
        logger.info(f"saved checkpoint: {path}")
        return path

    def state_dict(self):
        return {"freq": self.freq.state_dict()}

    def load_state_dict(self, state):
        self.freq.load_state_dict(state["freq"])
