"""Single-controller GRPO over RPC engine workers (CPU-runnable demo).

The deployment mode `areal_tpu.controller` + `areal_tpu.scheduler`
implement (reference: areal/scheduler/rpc/ + areal/controller/ single-
controller mode): algorithm code runs in ONE process; each engine worker
is a separate process owning its own jax mesh, driven over HTTP RPC.
Batches are chunked row-wise across the fleet by `TrainController` and
results merge back — the controller never touches a device.

This script is the smallest honest end-to-end slice: it spawns N worker
daemons via the real entry point

    python -m areal_tpu.scheduler.rpc_server --port <p>

waits for /health, then runs a few synthetic GRPO steps through
`TrainController` (logp -> advantages -> ppo_update) and prints the final
loss.  Swap `--model-path` onto the worker command line and raise the
sizes for a real run; the controller side does not change.

    python examples/rpc_controller/grpo_rpc_controller.py --workers 2
"""

import argparse
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, _REPO)

from areal_tpu.controller import TrainController  # noqa: E402
from areal_tpu.scheduler import RPCEngineClient  # noqa: E402
from areal_tpu.utils import network  # noqa: E402

VOCAB = 512  # matches the worker daemon's tiny fallback model


def _spawn_worker(port: int) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "areal_tpu.scheduler.rpc_server",
            "--port",
            str(port),
            "--pack-length-quantum",
            "16",
        ],
        cwd=_REPO,
        env=env,
    )


def _wait_healthy(addr: str, timeout: float = 180.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(f"http://{addr}/health", timeout=2)
            return
        except Exception:
            time.sleep(0.5)
    raise TimeoutError(f"worker at {addr} never became healthy")


def _synthetic_batch(rng, batch_size: int, seq_len: int, prompt_len: int):
    """GRPO-shaped rows: packed ids, loss on the completion span, binary
    rewards, behavior logprobs (a real run feeds rollout output here)."""
    ids = rng.integers(0, VOCAB, (batch_size, seq_len)).astype(np.int32)
    loss_mask = np.zeros((batch_size, seq_len), np.float32)
    loss_mask[:, prompt_len:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": np.ones((batch_size, seq_len), bool),
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (batch_size, seq_len)).astype(
            np.float32
        ),
        "rewards": rng.integers(0, 2, batch_size).astype(np.float32),
        "versions": np.zeros((batch_size, seq_len), np.int32),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=16)
    args = p.parse_args(argv)

    ports = [network.find_free_port() for _ in range(args.workers)]
    procs = [_spawn_worker(port) for port in ports]
    addrs = [f"127.0.0.1:{port}" for port in ports]
    try:
        for addr in addrs:
            _wait_healthy(addr)
        ctl = TrainController(
            [RPCEngineClient(a) for a in addrs], chunk_quantum=2
        )
        rng = np.random.default_rng(0)
        for step in range(args.steps):
            batch = _synthetic_batch(rng, args.batch_size, args.seq_len, 4)
            batch["prox_logp"] = ctl.compute_logp(batch)
            ctl.compute_advantages(batch)
            stats = ctl.ppo_update(batch)
            ctl.set_version(step + 1)
            print(
                f"step {step}: loss={stats[-1]['loss']:.4f} over "
                f"{args.workers} workers",
                flush=True,
            )
        print("ok")
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    main()
