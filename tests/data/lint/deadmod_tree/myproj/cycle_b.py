"""DEAD: see cycle_a."""

import myproj.cycle_a  # noqa: F401
