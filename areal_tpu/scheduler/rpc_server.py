"""HTTP RPC server exposing a train engine to a remote controller.

Behavioral counterpart of the reference's `EngineRPCServer`
(areal/scheduler/rpc/rpc_server.py:44): the single-controller deployment
mode where algorithm code runs in ONE controller process and drives N engine
worker processes over RPC.  The TPU-native shape: each worker process owns a
jax mesh (its local chips), the wire carries host numpy batches
(controller/batch.py), and device work runs on a dedicated thread so the
asyncio loop stays responsive for health checks.

Wire format (POST /call):
    body   = [8-byte LE kwargs length][kwargs JSON][DistributedBatch npz?]
    reply  = JSON  (scalar / stats results)
           | npz blob (array or batch results, content-type octet-stream)

Method dispatch is `getattr(worker, method)`; `update_weights`/`save`/`load`
re-hydrate their meta dataclasses from kwargs.  `return_batch=True` sends
the (possibly mutated) batch back — how in-place ops like
`compute_advantages` cross the wire.
"""

import asyncio
import concurrent.futures
from typing import Any, Optional

import numpy as np
from aiohttp import web

from areal_tpu.api.io_struct import SaveLoadMeta, WeightUpdateMeta
from areal_tpu.controller.batch import DistributedBatch
from areal_tpu.scheduler.wire import decode_frame
from areal_tpu.utils import logging, name_resolve, names, network

logger = logging.getLogger("rpc.server")


def _materialize(result):
    """json-serializable view of method results: async_stats engines return
    PendingTrainStats Mappings (deferred device fetches) — reading them
    here forces the fetch, which is correct at the RPC boundary (the
    result crosses a process edge as JSON)."""
    from areal_tpu.utils.stats import PendingTrainStats

    if isinstance(result, PendingTrainStats):
        return dict(result.materialize())
    if isinstance(result, list):
        return [_materialize(r) for r in result]
    return result


class EngineRPCServer:
    def __init__(self, worker: Any):
        self.worker = worker
        # one thread owns all device computation (XLA is not re-entrant from
        # many host threads the way we'd want; also serializes steps)
        self._exec = concurrent.futures.ThreadPoolExecutor(max_workers=1)

    async def call(self, request: web.Request) -> web.Response:
        body = await request.read()
        kwargs, blob = decode_frame(body)
        method = kwargs.pop("__method__")
        return_batch = kwargs.pop("return_batch", False)
        batch = DistributedBatch.from_bytes(blob).to_dict() if blob else None
        if return_batch and batch is None:
            # validate up front: falling through to DistributedBatch(None)
            # after the method ran would raise OUTSIDE the try below and
            # hand the client a bare 500 without the {"error": ...} contract
            return web.json_response(
                {"error": "return_batch=True requires a batch blob"},
                status=400,
            )

        # re-hydrate meta dataclasses
        if method == "update_weights" and "meta" in kwargs:
            kwargs["meta"] = WeightUpdateMeta(**kwargs["meta"])
        elif method in ("save", "load") and "meta" in kwargs:
            kwargs["meta"] = SaveLoadMeta(**kwargs["meta"])

        fn = getattr(self.worker, method, None)
        if fn is None:
            return web.json_response(
                {"error": f"no method {method!r}"}, status=404
            )
        loop = asyncio.get_running_loop()
        try:
            if batch is not None:
                result = await loop.run_in_executor(
                    self._exec, lambda: fn(batch, **kwargs)
                )
            else:
                result = await loop.run_in_executor(
                    self._exec, lambda: fn(**kwargs)
                )
        except Exception as e:  # noqa: BLE001 — errors cross the wire as 500s
            logger.exception(f"rpc call {method} failed")
            return web.json_response({"error": repr(e)}, status=500)

        if return_batch:
            blob_out = DistributedBatch(batch).to_bytes()
            return web.Response(
                body=blob_out, content_type="application/octet-stream"
            )
        if isinstance(result, np.ndarray):
            blob_out = DistributedBatch({"result": result}).to_bytes()
            return web.Response(
                body=blob_out, content_type="application/octet-stream"
            )
        if isinstance(result, dict) and any(
            isinstance(v, np.ndarray) for v in result.values()
        ):
            return web.Response(
                body=DistributedBatch(result).to_bytes(),
                content_type="application/octet-stream",
            )
        return web.json_response({"result": _materialize(result)})

    async def health(self, request: web.Request) -> web.Response:
        version = None
        get_version = getattr(self.worker, "get_version", None)
        if callable(get_version):
            try:
                version = get_version()
            except Exception:  # noqa: BLE001
                pass
        return web.json_response(
            {"status": "ok", "worker": type(self.worker).__name__,
             "version": version}
        )

    def app(self) -> web.Application:
        app = web.Application(client_max_size=4 * 1024**3)
        app.router.add_post("/call", self.call)
        app.router.add_get("/health", self.health)
        return app


def serve_engine(
    worker: Any,
    port: Optional[int] = None,
    experiment_name: str = "",
    trial_name: str = "",
    worker_idx: int = 0,
):
    """Blocking serve; registers in name_resolve under workers/rpc_engine."""
    port = port or network.find_free_port()
    server = EngineRPCServer(worker)
    if experiment_name:
        name_resolve.add(
            names.worker(experiment_name, trial_name, "rpc_engine", worker_idx),
            f"{network.gethostip()}:{port}",
            replace=True,
        )
    logger.info(f"engine rpc server on :{port} ({type(worker).__name__})")
    web.run_app(server.app(), port=port, print=None)


def main():
    """Worker-daemon entry point for the single-controller deployment:

        python -m areal_tpu.scheduler.rpc_server --model-path ... --port N

    spawns one engine worker process (the controller drives it over POST
    /call); a blank --model-path serves a tiny from-scratch actor, the CPU
    smoke shape (examples/rpc_controller/grpo_rpc_controller.py)."""
    import argparse

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo import JaxPPOActor
    from areal_tpu.models.model_config import TransformerConfig, tiny_config

    name_resolve.reconfigure_from_env()
    p = argparse.ArgumentParser()
    p.add_argument("--model-path", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--group-size", type=int, default=2)
    p.add_argument("--pack-length-quantum", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-6)
    p.add_argument("--experiment-name", default="")
    p.add_argument("--trial-name", default="")
    p.add_argument("--worker-idx", type=int, default=0)
    args = p.parse_args()
    if args.model_path:
        model_cfg = TransformerConfig.from_hf(args.model_path)
        dtype = "bfloat16"
    else:
        model_cfg = tiny_config(
            vocab_size=512, qkv_bias=True, hf_architecture="Qwen2ForCausalLM"
        )
        dtype = "float32"
    cfg = PPOActorConfig(
        experiment_name=args.experiment_name or "rpc-worker",
        trial_name=args.trial_name or "t",
        init_from_scratch=not args.model_path,
        path=args.model_path,
        dtype=dtype,
        param_dtype=dtype,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(lr=args.lr, warmup_steps_proportion=0.0),
        pack_length_quantum=args.pack_length_quantum,
        group_size=args.group_size,
        ppo_n_minibatches=1,
        adv_norm=NormConfig(
            mean_level="group",
            std_level="group",
            group_size=args.group_size,
        ),
    )
    actor = JaxPPOActor(cfg, model_config=model_cfg)
    actor.initialize(ft_spec=FinetuneSpec(1, 4096, 8))
    serve_engine(
        actor,
        port=args.port or None,
        experiment_name=args.experiment_name,
        trial_name=args.trial_name,
        worker_idx=args.worker_idx,
    )


if __name__ == "__main__":
    main()
