"""Model-side VLM tests: vision tower, mrope position ids/frequencies, and
the merged text+image forward (reference VLM path:
areal/engine/base_hf_engine.py:261-287 mrope construction + the qwen2-VL
tower loaded via AutoModelForImageTextToText)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.model_config import VisionConfig, tiny_config
from areal_tpu.models.vision import (
    forward_vlm_lm,
    init_vision_params,
    merge_image_embeds,
    mrope_cos_sin,
    mrope_position_ids,
    vision_forward,
)

VCFG = VisionConfig(
    patch_size=2,
    temporal_patch_size=1,
    in_channels=3,
    hidden_size=32,
    intermediate_size=64,
    num_layers=2,
    num_heads=4,
    spatial_merge_size=2,
    out_hidden_size=48,
)

IMG_TOK = 60


def _text_cfg():
    return tiny_config(
        vocab_size=64,
        hidden_size=48,
        num_heads=4,
        num_kv_heads=2,
        qkv_bias=True,
        dtype="float32",
        param_dtype="float32",
        hf_architecture="Qwen2VLForConditionalGeneration",
    ).replace(
        vision=VCFG,
        image_token_id=IMG_TOK,
        mrope_section=(2, 3, 3),  # head_dim 16 -> hd/2 = 8
    )


def test_mrope_position_ids_text_and_image():
    # layout: 2 text tokens, a 1x4x4-patch image (merge 2 -> 4 placeholders),
    # 2 text tokens
    ids = np.array([5, 6] + [IMG_TOK] * 4 + [7, 8])
    grid = np.array([[1, 4, 4]])
    pos = mrope_position_ids(ids, grid, IMG_TOK, spatial_merge_size=2)
    assert pos.shape == (3, 8)
    # text prefix: all rows advance together
    np.testing.assert_array_equal(pos[:, 0], [0, 0, 0])
    np.testing.assert_array_equal(pos[:, 1], [1, 1, 1])
    # image block starts at offset 2: temporal constant, (h, w) grid 2x2
    np.testing.assert_array_equal(pos[0, 2:6], [2, 2, 2, 2])
    np.testing.assert_array_equal(pos[1, 2:6], [2, 2, 3, 3])
    np.testing.assert_array_equal(pos[2, 2:6], [2, 3, 2, 3])
    # text resumes at max(grid extent) past the offset: 2 + max(1,2,2) = 4
    np.testing.assert_array_equal(pos[:, 6], [4, 4, 4])
    np.testing.assert_array_equal(pos[:, 7], [5, 5, 5])


def test_mrope_cos_sin_sections():
    hd = 16
    pos3 = jnp.asarray(
        np.stack(
            [np.full((1, 4), 10), np.full((1, 4), 20), np.full((1, 4), 30)]
        )
    )
    cos, sin = mrope_cos_sin(pos3, hd, 10000.0, (2, 3, 3))
    assert cos.shape == (1, 4, hd // 2)
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    # first 2 bands follow the temporal row (pos 10), next 3 height (20),
    # last 3 width (30)
    expect = np.cos(np.array([10, 10, 20, 20, 20, 30, 30, 30]) * inv)
    np.testing.assert_allclose(np.asarray(cos)[0, 0], expect, rtol=1e-5)


def test_vision_forward_shapes_and_image_isolation():
    rng = np.random.default_rng(0)
    params = init_vision_params(VCFG, jax.random.PRNGKey(0))
    # two images of 4x4 patches each -> 32 patches, 8 merged embeddings
    patches = rng.normal(size=(32, VCFG.patch_dim)).astype(np.float32)
    img_ids = np.repeat([0, 1], 16).astype(np.int32)
    out = vision_forward(params, VCFG, jnp.asarray(patches), jnp.asarray(img_ids))
    assert out.shape == (8, VCFG.out_hidden_size)

    # perturbing image 1's pixels must not change image 0's embeddings
    patches2 = patches.copy()
    patches2[16:] += 1.0
    out2 = vision_forward(params, VCFG, jnp.asarray(patches2), jnp.asarray(img_ids))
    np.testing.assert_allclose(
        np.asarray(out[:4]), np.asarray(out2[:4]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out[4:]), np.asarray(out2[4:]))


def test_merge_image_embeds_scatter_order():
    B, T, D = 1, 6, 4
    text = jnp.zeros((B, T, D))
    ids = jnp.asarray([[1, IMG_TOK, IMG_TOK, 2, IMG_TOK, 3]])
    vis = jnp.asarray(np.arange(3 * D, dtype=np.float32).reshape(3, D) + 100)
    merged = merge_image_embeds(text, ids, vis, IMG_TOK)
    np.testing.assert_array_equal(np.asarray(merged[0, 1]), np.asarray(vis[0]))
    np.testing.assert_array_equal(np.asarray(merged[0, 2]), np.asarray(vis[1]))
    np.testing.assert_array_equal(np.asarray(merged[0, 4]), np.asarray(vis[2]))
    assert np.asarray(merged[0, 0]).sum() == 0  # text rows untouched


def test_forward_vlm_lm_end_to_end_grads():
    from areal_tpu.models import init_params

    cfg = _text_cfg()
    key = jax.random.PRNGKey(1)
    params = init_params(cfg, key)
    params["vision"] = init_vision_params(VCFG, jax.random.PRNGKey(2))

    rng = np.random.default_rng(3)
    # one 4x4-patch image -> 4 merged embeddings -> 4 placeholder tokens
    T = 12
    ids = np.full((1, T), 7, np.int32)
    ids[0, 2:6] = IMG_TOK
    patches = rng.normal(size=(16, VCFG.patch_dim)).astype(np.float32)
    img_ids = np.zeros(16, np.int32)
    positions = np.arange(T, dtype=np.int32)[None]
    segs = np.zeros((1, T), np.int32)
    mpos = mrope_position_ids(ids[0], np.array([[1, 4, 4]]), IMG_TOK)[:, None, :]

    def loss_fn(p):
        out = forward_vlm_lm(
            p, cfg,
            jnp.asarray(ids), jnp.asarray(positions), jnp.asarray(segs),
            jnp.asarray(patches), jnp.asarray(img_ids),
            mrope_positions=jnp.asarray(mpos),
        )
        logits = out.hidden @ out.head
        labels = jnp.roll(jnp.asarray(ids), -1, axis=-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, labels[..., None], axis=-1)
        )

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # gradients flow into the vision tower through the merged embeddings
    g = jax.tree_util.tree_map(lambda a: float(jnp.sum(jnp.abs(a))), grads["vision"])
    assert g["patch_embed"] > 0
    assert g["merger_fc2"] > 0
