# areal-lint: disable=dead-module recipe library surface consumed by user training scripts (reference parity: AReaL recipe/); covered by tests/test_aent.py
from areal_tpu.recipes.aent import AEntConfig, AEntPPOActorConfig, JaxAEntPPOActor

__all__ = ["AEntConfig", "AEntPPOActorConfig", "JaxAEntPPOActor"]
