"""Measure trainer→server weight-sync latency: transfer vs disk path.

Transfer = binary octet-stream chunks into server memory
(gen/server.py /update_weights_chunk); disk = HF safetensors snapshot +
/update_weights_from_disk.  On a single-core host the two ends of the
transfer serialize, so transfer_vs_disk > 1 here does NOT mean the wire
path lost — see docs/perf.md "Weight-sync latency" for the decomposition
and regime analysis.  Host/network-bound, so it runs anywhere:

    JAX_PLATFORMS=cpu python scripts/bench_weight_sync.py

Prints one JSON line; the numbers live in docs/perf.md.
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import asyncio
    import threading

    import numpy as np
    from aiohttp import web

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.gen.server import GenServer
    from areal_tpu.models import init_params
    from areal_tpu.models.hf import save_hf_checkpoint
    from areal_tpu.models.model_config import qwen25_1p5b
    from areal_tpu.utils.http import request_with_retry_sync

    cfg = qwen25_1p5b().replace(dtype="bfloat16", param_dtype="bfloat16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    n_bytes = sum(int(np.prod(p.shape)) * 2 for p in jax.tree_util.tree_leaves(params))

    engine = GenEngine(cfg, params=params, n_slots=1, max_seq_len=128,
                       prompt_bucket=16)
    server = GenServer(engine)
    server.start()
    holder, started = {}, threading.Event()

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def go():
            runner = web.AppRunner(server.app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["addr"] = f"127.0.0.1:{runner.addresses[0][1]}"
            started.set()

        loop.run_until_complete(go())
        loop.run_forever()

    threading.Thread(target=_run, daemon=True).start()
    assert started.wait(30)
    addr = holder["addr"]

    # --- transfer path: trainer-side push through the engine hook -------
    from areal_tpu.api.config import TrainEngineConfig
    from areal_tpu.api.io_struct import WeightUpdateMeta
    from areal_tpu.engine.jax_train import JaxTrainEngine

    trainer = JaxTrainEngine(
        TrainEngineConfig(
            experiment_name="wsync", trial_name="t",
            init_from_scratch=True, dtype="bfloat16",
            param_dtype="bfloat16", optimizer=None,
        ),
        model_config=cfg,
    )
    trainer.initialize(ft_spec=None)
    # settle async param initialisation: measuring from here would charge
    # jit-init wait time to the transfer path
    jax.block_until_ready(trainer.params)
    os.environ["AREAL_LLM_SERVER_ADDRS"] = addr
    # abort-commit path pinned: the bench measures the stream+commit
    # choreography the non-live fleet default used through r4
    meta = WeightUpdateMeta.from_transfer("wsync", "t", live_commit=False)
    t0 = time.perf_counter()
    trainer._update_weights_transfer(meta)
    transfer_s = time.perf_counter() - t0

    # --- disk path: HF snapshot + server-side load ----------------------
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "v1")
        t0 = time.perf_counter()
        host = trainer._export_params()
        save_hf_checkpoint(host, cfg, path, save_dtype="bfloat16")
        save_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        request_with_retry_sync(
            addr=addr, endpoint="/update_weights_from_disk",
            payload={"path": path, "version": 2}, timeout=600,
        )
        load_s = time.perf_counter() - t0

    print(json.dumps({
        "model": "qwen25_1p5b",
        "model_bytes_bf16": n_bytes,
        "transfer_path_seconds": round(transfer_s, 2),
        "disk_path_seconds": round(save_s + load_s, 2),
        "disk_save_seconds": round(save_s, 2),
        "disk_load_seconds": round(load_s, 2),
        "transfer_vs_disk": round(transfer_s / max(save_s + load_s, 1e-9), 3),
    }))


if __name__ == "__main__":
    main()
