"""GSM8K math dataset (reference: areal/dataset/gsm8k.py).

Yields dicts {messages, query_id, answer}; the RLVR workflow tokenizes via
the chat template and the reward fn checks the final "#### N" answer.
"""

import re
from typing import Optional

from areal_tpu.dataset import register_dataset

PROMPT_SUFFIX = (
    "\nPlease reason step by step, and put your final answer within \\boxed{}."
)


def gsm8k_answer(solution: str) -> str:
    m = re.search(r"####\s*([\-0-9\.,/]+)", solution)
    return m.group(1).replace(",", "").strip() if m else solution.strip()


@register_dataset("gsm8k")
def load_gsm8k(
    path: str = "openai/gsm8k",
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    import datasets as hf_datasets

    if path.endswith(".jsonl") or path.endswith(".json"):
        ds = hf_datasets.load_dataset("json", data_files=path, split="train")
    else:
        ds = hf_datasets.load_dataset(path, "main", split=split)

    def to_sample(x, idx):
        return {
            "messages": [
                {"role": "user", "content": x["question"] + PROMPT_SUFFIX}
            ],
            "query_id": str(idx),
            "answer": gsm8k_answer(x["answer"]),
        }

    ds = ds.map(to_sample, with_indices=True, remove_columns=ds.column_names)
    if max_length is not None and tokenizer is not None:
        ds = ds.filter(
            lambda x: len(
                tokenizer.apply_chat_template(
                    x["messages"], add_generation_prompt=True, tokenize=True
                )
            )
            <= max_length
        )
    return ds
