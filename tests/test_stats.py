import numpy as np
import pytest

from areal_tpu.utils.stats import ReduceType, StatsTracker


def test_masked_avg_and_sum():
    t = StatsTracker("ppo")
    mask = np.array([True, True, False, True])
    t.denominator(valid=mask)
    t.stat(denominator="valid", loss=np.array([1.0, 2.0, 100.0, 3.0]))
    t.stat(
        denominator="valid",
        reduce_type=ReduceType.SUM,
        n_tokens=np.array([1.0, 1.0, 1.0, 1.0]),
    )
    out = t.export()
    assert out["ppo/loss"] == pytest.approx(2.0)
    assert out["ppo/n_tokens"] == pytest.approx(3.0)
    assert out["ppo/valid/count"] == 3.0


def test_scopes_nest():
    t = StatsTracker()
    with t.scope("actor"):
        with t.scope("mb0"):
            t.scalar(lr=0.1)
    out = t.export()
    assert out["actor/mb0/lr"] == pytest.approx(0.1)


def test_min_max_reduce():
    t = StatsTracker()
    m = np.ones(3, dtype=bool)
    t.denominator(all=m)
    t.stat(denominator="all", reduce_type=ReduceType.MAX, v=np.array([1.0, 5.0, 3.0]))
    t.denominator(all2=m)
    t.stat(denominator="all2", reduce_type=ReduceType.MIN, w=np.array([1.0, 5.0, 3.0]))
    out = t.export()
    assert out["v"] == 5.0
    assert out["w"] == 1.0


def test_export_resets():
    t = StatsTracker()
    t.scalar(x=1.0)
    assert "x" in t.export()
    assert "x" not in t.export()


def test_export_key_filter():
    t = StatsTracker()
    t.scalar(**{"a": 1.0})
    with t.scope("keep"):
        t.scalar(b=2.0)
    out = t.export(key="keep")
    assert "keep/b" in out and "a" not in out
    # unexported keys survive
    assert "a" in t.export()


def test_multiple_records_accumulate():
    t = StatsTracker()
    for v in ([1.0, 2.0], [3.0, 4.0]):
        arr = np.array(v)
        t.denominator(d=np.ones(2, dtype=bool))
        t.stat(denominator="d", x=arr)
    assert t.export()["x"] == pytest.approx(2.5)


def test_timing():
    t = StatsTracker()
    with t.record_timing("step"):
        pass
    out = t.export()
    assert "time_perf/step" in out


def test_shape_mismatch_raises():
    t = StatsTracker()
    t.denominator(d=np.ones(2, dtype=bool))
    with pytest.raises(ValueError):
        t.stat(denominator="d", x=np.ones(3))
    with pytest.raises(ValueError):
        t.denominator(bad=np.ones(2, dtype=np.float32))
    with pytest.raises(ValueError):
        t.stat(denominator="missing", x=np.ones(2))


def test_repeated_stats_against_one_denominator():
    # two stat() calls after one denominator(): both must count
    t = StatsTracker()
    t.denominator(d=np.ones(2, dtype=bool))
    t.stat(denominator="d", loss=np.array([1.0, 1.0]))
    t.stat(denominator="d", loss=np.array([3.0, 3.0]))
    assert t.export()["loss"] == pytest.approx(2.0)
