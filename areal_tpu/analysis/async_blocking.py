"""C3 — blocking calls inside `async def` bodies.

The generation server, router, and remote client all run on asyncio event
loops that also carry health probes, weight-update control traffic, and
the staleness gate; one ``time.sleep`` or synchronous HTTP call in a
handler stalls every request on the loop.  This checker flags the known
blocking families lexically inside any ``async def`` body:

- ``time.sleep`` (use ``await asyncio.sleep``);
- synchronous HTTP: ``requests.*``, ``urllib.request.urlopen``;
- blocking file I/O: builtin ``open``/``io.open``, ``Path.read_text`` /
  ``write_text`` / ``read_bytes`` / ``write_bytes``;
- subprocess waits: ``subprocess.run/call/check_call/check_output``,
  ``os.system``/``os.popen``.

Nested synchronous ``def``s inside an async function are exempt — they
are the standard vehicle for ``loop.run_in_executor`` offloads; the rule
covers what the event loop itself executes.
"""

import ast
from typing import List

from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

RULE = "async-blocking"

_BLOCKING_EXACT = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "open": "blocking file I/O on the event loop; offload via "
    "run_in_executor or read before entering async code",
    "io.open": "blocking file I/O on the event loop",
    "os.system": "blocks the loop until the child exits",
    "os.popen": "blocks the loop until the child exits",
    "subprocess.run": "blocks the loop until the child exits; use "
    "asyncio.create_subprocess_exec",
    "subprocess.call": "blocks the loop until the child exits",
    "subprocess.check_call": "blocks the loop until the child exits",
    "subprocess.check_output": "blocks the loop until the child exits",
    "urllib.request.urlopen": "synchronous HTTP on the event loop; use "
    "the aiohttp session",
}
_BLOCKING_PREFIXES = {
    "requests.": "synchronous HTTP on the event loop; use the aiohttp "
    "session",
}
_BLOCKING_METHODS = {
    "read_text",
    "write_text",
    "read_bytes",
    "write_bytes",
}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _walk_async_body(fn):
    """Descendants of an async def, not descending into nested defs (sync
    nested defs are executor fodder; nested async defs are scanned on
    their own when the module walk reaches them)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def check_async_blocking(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if sf.tree is None:
        return findings
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in _walk_async_body(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            why = _BLOCKING_EXACT.get(name)
            if why is None:
                for pref, pwhy in _BLOCKING_PREFIXES.items():
                    if name.startswith(pref):
                        why = pwhy
                        break
            if (
                why is None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
            ):
                name = node.func.attr
                why = "blocking file I/O on the event loop"
            if why is not None:
                findings.append(
                    apply_suppression(
                        sf,
                        Finding(
                            RULE,
                            sf.rel,
                            node.lineno,
                            f"`{name}(...)` inside `async def {fn.name}` "
                            f"blocks the event loop — {why}",
                        ),
                    )
                )
    return findings
