"""C10 positive fixture: the broken server side — `--width` renamed away
from the chained flag, the parsed value dropped before the engine call,
and an uncovered extra flag."""

import argparse


class TinyEngine:
    pass


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--depth", type=int, default=1)
    p.add_argument("--extra", type=int, default=0)  # VIOLATION: uncovered
    args = p.parse_args()
    # VIOLATION: width is chained but never passed (and --width is gone)
    return TinyEngine(depth=args.depth)
