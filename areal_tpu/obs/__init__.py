"""Trace analytics for the lifecycle telemetry layer (ISSUE 14).

The telemetry PR (ISSUE 10) made the fleet *emit* evidence — lifecycle
spans riding a ``trace_id`` from client submit through admission,
prefill, per-tier decode chunks, interrupt/resume, reward, and train
consumption.  This package *consumes* it, strictly offline: everything
here parses dumped JSONL (or an in-memory event list) and never touches
engine internals, so it can never put work on a hot path.

- :mod:`areal_tpu.obs.trace` — per-trajectory records, the trace
  completeness linter, and per-stage latency decomposition with an
  accounting identity (stage sum ≈ client-measured end-to-end).
- :mod:`areal_tpu.obs.slo` — SLO report generator (JSON + markdown):
  p50/p90/p99 per stage, TTFT, inter-token latency, goodput, staleness
  and pause-window distributions.  ``python -m areal_tpu.obs.slo``.
- :mod:`areal_tpu.obs.workload` — arrival-process extraction from a
  recorded trace plus a seeded synthetic mixed workload (chat bursts,
  GRPO groups, long-context stragglers) for `scripts/bench_replay.py`.
"""

from areal_tpu.obs import slo, trace, workload  # noqa: F401
