"""Multi-process distributed training tests.

Spawns real OS processes that join one jax.distributed runtime over
CPU devices (2 procs x 4 devices), mirroring the reference's
torchrun-spawning driver tests (SURVEY.md §4.1 "multi-process distributed
tests").  Verifies: global mesh bring-up via the AREAL_* env contract,
DP-head-only rollout with batch broadcast, and that a full PPO update over
a dp2(x-process) x fsdp2 x tp2 mesh produces identical replicated losses on
every process.
"""

import os
import subprocess
import sys

import pytest

from areal_tpu.utils.network import find_free_port

WORKER = os.path.join(os.path.dirname(__file__), "mp", "train_worker.py")


def test_two_process_train_step():
    port = find_free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AREAL_COORDINATOR=f"127.0.0.1:{port}",
            AREAL_NUM_PROCESSES="2",
            AREAL_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=570)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"DONE proc={pid}" in out, out[-2000:]

    # replicated loss/grad-norm must agree exactly across processes
    def results(out):
        return sorted(
            line.split("proc=")[1].split(" ", 1)[1]
            for line in out.splitlines()
            if line.startswith("RESULT")
        )

    r0, r1 = results(outs[0]), results(outs[1])
    assert len(r0) == 2
    assert r0 == r1, f"\nproc0: {r0}\nproc1: {r1}"
