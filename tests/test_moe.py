"""MoE block + expert-parallel mesh tests (VERDICT round-1 next-step #10).

Coverage model: the reference's MoE stack (realhf/impl/model/modules/moe/,
Megatron EP in megatron_engine.py) — here the GShard-style dense-dispatch
block (models/moe.py) and the `ep` mesh axis.
"""

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models import forward_lm, init_params
from areal_tpu.models.model_config import tiny_config
from areal_tpu.models.moe import expert_capacity, moe_ffn
from areal_tpu.models.transformer import _mlp


def _moe_cfg(**kw):
    base = dict(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_layers=2,
        num_heads=2,
        num_kv_heads=2,
        num_experts=4,
        num_experts_per_tok=2,
        moe_capacity_factor=4.0,  # ample capacity: no token dropping
        dtype="float32",
    )
    base.update(kw)
    return tiny_config(**base)


def test_identical_experts_match_dense_mlp():
    """With every expert = the same dense MLP and ample capacity, routing is
    irrelevant: MoE output must equal the dense block exactly."""
    cfg = _moe_cfg()
    rng = np.random.default_rng(0)
    D, F = cfg.hidden_size, cfg.intermediate_size
    w_gate = jnp.asarray(rng.normal(0, 0.05, (D, F)), jnp.float32)
    w_up = jnp.asarray(rng.normal(0, 0.05, (D, F)), jnp.float32)
    w_down = jnp.asarray(rng.normal(0, 0.05, (F, D)), jnp.float32)
    E = cfg.num_experts
    lp = {
        "router": jnp.asarray(rng.normal(0, 1.0, (D, E)), jnp.float32),
        "w_gate": jnp.broadcast_to(w_gate, (E, D, F)),
        "w_up": jnp.broadcast_to(w_up, (E, D, F)),
        "w_down": jnp.broadcast_to(w_down, (E, F, D)),
    }
    h = jnp.asarray(rng.normal(size=(2, 16, D)), jnp.float32)
    out, aux = moe_ffn(cfg, lp, h, jnp.float32)
    dense = _mlp({"mlp": {"w_gate": w_gate, "w_up": w_up, "w_down": w_down}},
                 h, jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)
    # balanced-ish routing keeps the Switch aux loss near its floor of 1.0
    assert 0.9 < float(aux) < 4.0


def test_capacity_drops_tokens():
    """With capacity 8 and every token routed to one expert, overflow tokens
    contribute nothing (their combine weights are zero)."""
    cfg = _moe_cfg(num_experts_per_tok=1, moe_capacity_factor=0.01)
    rng = np.random.default_rng(1)
    D, F, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    router = np.zeros((D, E), np.float32)
    lp = {
        "router": jnp.asarray(router),
        "w_gate": jnp.asarray(rng.normal(0, 0.05, (E, D, F)), jnp.float32),
        "w_up": jnp.asarray(rng.normal(0, 0.05, (E, D, F)), jnp.float32),
        "w_down": jnp.asarray(rng.normal(0, 0.05, (E, F, D)), jnp.float32),
    }
    h = jnp.asarray(rng.normal(size=(1, 64, D)), jnp.float32)
    out, _ = moe_ffn(cfg, lp, h, jnp.float32)
    # zero router logits tie-break to expert 0 for every token; capacity is
    # 8 so at most 8 token outputs are nonzero
    nonzero_rows = np.abs(np.asarray(out)[0]).sum(-1) > 1e-9
    assert nonzero_rows.sum() == expert_capacity(64, E, 1, 0.01)


def _rand_lp(rng, D, F, E, router_scale=1.0):
    return {
        "router": jnp.asarray(rng.normal(0, router_scale, (D, E)), jnp.float32),
        "w_gate": jnp.asarray(rng.normal(0, 0.05, (E, D, F)), jnp.float32),
        "w_up": jnp.asarray(rng.normal(0, 0.05, (E, D, F)), jnp.float32),
        "w_down": jnp.asarray(rng.normal(0, 0.05, (E, F, D)), jnp.float32),
    }


def test_dropless_matches_ample_capacity():
    """Where the capacity path is drop-free, dropless must agree exactly —
    same routing, same experts, different dispatch plumbing."""
    cfg = _moe_cfg()  # factor 4.0: no drops
    rng = np.random.default_rng(5)
    D, F, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    lp = _rand_lp(rng, D, F, E)
    h = jnp.asarray(rng.normal(size=(2, 16, D)), jnp.float32)
    out_cap, aux_cap = moe_ffn(cfg, lp, h, jnp.float32)
    out_dl, aux_dl = moe_ffn(cfg.replace(moe_impl="dropless"), lp, h, jnp.float32)
    np.testing.assert_allclose(np.asarray(out_dl), np.asarray(out_cap),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(aux_dl), float(aux_cap), rtol=1e-6)


def test_dropless_no_drops_under_imbalance():
    """ADVICE r3: all tokens routed to ONE expert (zero router logits,
    k=1) — the capacity default silently zeroes overflow rows; dropless
    must equal the dense single-expert oracle for EVERY token."""
    cfg = _moe_cfg(num_experts_per_tok=1, moe_capacity_factor=0.01,
                   moe_impl="dropless")
    rng = np.random.default_rng(6)
    D, F, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    lp = _rand_lp(rng, D, F, E, router_scale=0.0)
    h = jnp.asarray(rng.normal(size=(1, 64, D)), jnp.float32)
    out, _ = moe_ffn(cfg, lp, h, jnp.float32)
    oracle = _mlp(
        {"mlp": {"w_gate": lp["w_gate"][0], "w_up": lp["w_up"][0],
                 "w_down": lp["w_down"][0]}},
        h, jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=2e-4, atol=2e-5)


def test_dropless_batch_size_invariant():
    """Capacity depends on total tokens, so capacity-mode outputs vary with
    batch composition under imbalance; dropless outputs must not."""
    cfg = _moe_cfg(moe_impl="dropless")
    rng = np.random.default_rng(7)
    D, F, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    lp = _rand_lp(rng, D, F, E)
    h = jnp.asarray(rng.normal(size=(1, 64, D)), jnp.float32)
    full, _ = moe_ffn(cfg, lp, h, jnp.float32)
    small, _ = moe_ffn(cfg, lp, h[:, :8], jnp.float32)
    np.testing.assert_allclose(np.asarray(full)[:, :8], np.asarray(small),
                               rtol=2e-4, atol=2e-5)


def test_dropless_gradients_flow():
    """The sort + ragged_dot + scatter-add path must be differentiable end
    to end (HF-loaded MoE checkpoints train through it)."""
    cfg = _moe_cfg(moe_impl="dropless")
    rng = np.random.default_rng(8)
    D, F, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    lp = _rand_lp(rng, D, F, E)
    h = jnp.asarray(rng.normal(size=(1, 16, D)), jnp.float32)

    def loss(lp):
        out, aux = moe_ffn(cfg, lp, h, jnp.float32)
        return jnp.sum(out**2) + 0.01 * aux

    grads = jax.grad(loss)(lp)
    for k, g in grads.items():
        assert np.all(np.isfinite(np.asarray(g))), k
        assert float(jnp.abs(g).sum()) > 0.0, k


def test_moe_model_trains_on_ep_mesh():
    """Full MoE model: forward_lm carries the aux loss, gradients flow, and
    a PPO update runs on a dp2 x ep2 x tp2 mesh (expert dim sharded)."""
    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo import JaxPPOActor

    cfg = PPOActorConfig(
        experiment_name="moe", trial_name="t", init_from_scratch=True,
        dtype="float32", param_dtype="float32", gradient_checkpointing=True,
        mesh=MeshConfig(
            data_parallel_size=2, expert_parallel_size=2,
            tensor_parallel_size=2,
        ),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        pack_length_quantum=32, max_pack_length=64,
        group_size=2, ppo_n_minibatches=1,
        adv_norm=NormConfig(mean_level="group", std_level="group", group_size=2),
    )
    actor = JaxPPOActor(cfg, model_config=_moe_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 16, 4))
    assert actor.mesh.shape["ep"] == 2

    rng = np.random.default_rng(2)
    B, L = 8, 24
    batch = {
        "input_ids": rng.integers(0, 64, (B, L)).astype(np.int32),
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.pad(np.ones((B, L - 4), np.float32), ((0, 0), (4, 0))),
        "logprobs": rng.normal(-1, 0.1, (B, L)).astype(np.float32),
        "rewards": rng.integers(0, 2, B).astype(np.float32),
        "versions": np.zeros((B, L), np.int32),
    }
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    stats = actor.ppo_update(batch)
    assert np.isfinite(stats[-1]["loss"])
    assert "moe_aux_loss" in stats[-1]


def test_moe_generation():
    """MoE model serves through the generation engine (prefill + decode)."""
    from areal_tpu.gen.engine import GenEngine, GenRequest

    mcfg = _moe_cfg(eos_token_id=None)
    params = init_params(mcfg, jax.random.PRNGKey(0))
    engine = GenEngine(mcfg, params=params, n_slots=2, max_seq_len=64,
                       prompt_bucket=16)
    req = GenRequest(rid="m", input_ids=[1, 2, 3], max_new_tokens=6,
                     temperature=0.0)
    engine.generate_blocking([req])
    assert len(req.output_tokens) == 6


def test_moe_generation_expert_parallel():
    """VERDICT r2 #10: ep>1 serving mesh shards the [E, ., .] expert leaves
    (reference inference-side expert dims, alloc_mode.py:80-117); greedy
    outputs must match the replicated ep=1 engine."""
    from areal_tpu.gen.engine import GenEngine, GenRequest

    mcfg = _moe_cfg(eos_token_id=None)
    params = init_params(mcfg, jax.random.PRNGKey(0))
    out = {}
    for ep in (1, 2):
        engine = GenEngine(mcfg, params=params, n_slots=2, max_seq_len=64,
                           prompt_bucket=16, ep=ep)
        if ep > 1:
            # expert leaves actually sharded over the ep axis
            leaf = engine.params["layers"]["moe"]["w_gate"]
            assert "ep" in str(leaf.sharding.spec)
        req = GenRequest(rid=f"m{ep}", input_ids=[1, 2, 3], max_new_tokens=6,
                         temperature=0.0)
        engine.generate_blocking([req])
        out[ep] = list(req.output_tokens)
    assert out[1] == out[2], out

    # ep must divide num_experts, and dense models reject ep>1
    import pytest as _pytest

    with _pytest.raises(ValueError, match="ep=3"):
        GenEngine(mcfg, params=params, ep=3)
