from areal_tpu.agent.api import Agent, AgentWorkflow, make_agent, register_agent
from areal_tpu.agent.math_agent import MathMultiTurnAgent, MathSingleStepAgent
from areal_tpu.agent.search_agent import SearchQAAgent
from areal_tpu.agent.tir_agent import TIRMathAgent

__all__ = [
    "Agent",
    "AgentWorkflow",
    "make_agent",
    "register_agent",
    "MathMultiTurnAgent",
    "MathSingleStepAgent",
    "SearchQAAgent",
    "TIRMathAgent",
]
