"""TTL'd distributed KV service + name_resolve backend.

Capability counterpart of the reference's etcd3 name_resolve backend
(areal/utils/name_resolve.py:411: leased keys that expire when their owner
dies, shared by every process in a multi-host fleet).  The etcd3 client
library is not part of this image, so the same semantics are provided
first-party: a single aiohttp KV server (start it anywhere reachable —
typically the launcher host) and an HTTP repository whose TTL'd keys are
kept alive by a background lease-refresh thread; keys whose owner stops
refreshing disappear after their TTL, which is exactly the liveness signal
`watch_names` peer-death detection consumes.

Server:  python -m areal_tpu.utils.kv_store --port 18999
Client:  AREAL_NAME_RESOLVE=http:<host>:18999   (launchers pass this down)
"""

import argparse
import asyncio
import threading
import time
from typing import Dict, List, Optional, Tuple

from areal_tpu.utils import logging
from areal_tpu.utils.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    NameRecordRepository,
)

logger = logging.getLogger("kv_store")

DEFAULT_TTL = 30.0  # seconds a leased key survives without a refresh


class KVServer:
    """In-memory hierarchical KV with per-key TTL leases."""

    def __init__(self, sweep_interval: float = 1.0):
        # name -> (value, expiry_monotonic | None)
        self._store: Dict[str, Tuple[str, Optional[float]]] = {}
        self._lock = asyncio.Lock()
        self.sweep_interval = sweep_interval
        self._sweeper: Optional[asyncio.Task] = None

    # ------------------------------ core -------------------------------

    def _expired(self, name: str) -> bool:
        _, exp = self._store[name]
        return exp is not None and exp < time.monotonic()

    def _prune(self) -> None:
        now = time.monotonic()
        dead = [
            k for k, (_, exp) in self._store.items()
            if exp is not None and exp < now
        ]
        for k in dead:
            del self._store[k]
        if dead:
            logger.info(f"expired {len(dead)} leased keys")

    # ----------------------------- handlers ----------------------------

    async def add(self, request):
        from aiohttp import web

        body = await request.json()
        name = body["name"].rstrip("/")
        ttl = body.get("ttl")
        async with self._lock:
            self._prune()
            if name in self._store and not body.get("replace", False):
                return web.json_response({"error": "exists"}, status=409)
            self._store[name] = (
                str(body["value"]),
                time.monotonic() + ttl if ttl else None,
            )
        return web.json_response({"ok": True})

    async def get(self, request):
        from aiohttp import web

        name = request.query["name"].rstrip("/")
        async with self._lock:
            if name not in self._store or self._expired(name):
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response({"value": self._store[name][0]})

    async def keys(self, request):
        from aiohttp import web

        root = request.query["root"].rstrip("/")
        prefix = root + "/"
        async with self._lock:
            self._prune()
            found = sorted(
                k for k in self._store if k.startswith(prefix) or k == root
            )
            values = [self._store[k][0] for k in found]
        return web.json_response({"keys": found, "values": values})

    async def delete(self, request):
        from aiohttp import web

        body = await request.json()
        name = body["name"].rstrip("/")
        async with self._lock:
            removed = self._store.pop(name, None)
        if removed is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"ok": True})

    async def clear(self, request):
        from aiohttp import web

        body = await request.json()
        prefix = body["root"].rstrip("/") + "/"
        async with self._lock:
            dead = [
                k for k in self._store
                if k.startswith(prefix) or k == body["root"].rstrip("/")
            ]
            for k in dead:
                del self._store[k]
        return web.json_response({"ok": True, "removed": len(dead)})

    async def touch(self, request):
        """Lease refresh: extend the TTL of every named key the caller
        still owns (the etcd3 keepalive equivalent)."""
        from aiohttp import web

        body = await request.json()
        ttl = float(body.get("ttl", DEFAULT_TTL))
        refreshed = 0
        async with self._lock:
            for name in body.get("names", ()):
                name = name.rstrip("/")
                if name in self._store and not self._expired(name):
                    value, _ = self._store[name]
                    self._store[name] = (value, time.monotonic() + ttl)
                    refreshed += 1
        return web.json_response({"ok": True, "refreshed": refreshed})

    async def health(self, request):
        from aiohttp import web

        async with self._lock:
            return web.json_response(
                {"status": "ok", "keys": len(self._store)}
            )

    # ------------------------------ wiring ------------------------------

    async def _sweep(self):
        while True:
            await asyncio.sleep(self.sweep_interval)
            async with self._lock:
                self._prune()

    def app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/kv/add", self.add)
        app.router.add_get("/kv/get", self.get)
        app.router.add_get("/kv/keys", self.keys)
        app.router.add_post("/kv/delete", self.delete)
        app.router.add_post("/kv/clear", self.clear)
        app.router.add_post("/kv/touch", self.touch)
        app.router.add_get("/health", self.health)

        async def _start_sweeper(app):
            self._sweeper = asyncio.create_task(self._sweep())

        async def _stop_sweeper(app):
            if self._sweeper is not None:
                self._sweeper.cancel()

        app.on_startup.append(_start_sweeper)
        app.on_cleanup.append(_stop_sweeper)
        return app


class HttpNameRecordRepository(NameRecordRepository):
    """name_resolve backend over a KVServer; TTL'd keys auto-refresh from a
    keepalive thread, so keys of crashed processes expire — the reference's
    etcd3 lease behavior (name_resolve.py:411)."""

    def __init__(self, addr: str, ttl: float = DEFAULT_TTL):
        import requests

        self.addr = addr
        self.ttl = ttl
        # requests.Session is NOT thread-safe: the keepalive thread and the
        # caller thread must each get their own (ADVICE r3 — sharing one
        # races on the connection pool under load)
        self._local = threading.local()
        self._to_delete: List[str] = []
        self._leased: List[str] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._keepalive: Optional[threading.Thread] = None

    # ------------------------- http plumbing ---------------------------

    def _url(self, path: str) -> str:
        return f"http://{self.addr}{path}"

    def _request(self, method: str, path: str, *, retries: int = 3, **kw):
        """Transient-error retry: watch_names/wait poll loops only guard
        against NameEntryNotFoundError, so a single KV-server blip must not
        escape as a connection error and silently kill a watcher thread."""
        import requests

        session = getattr(self._local, "session", None)
        if session is None:
            session = self._local.session = requests.Session()
        last: Optional[BaseException] = None
        for attempt in range(retries):
            try:
                return session.request(
                    method, self._url(path), timeout=30, **kw
                )
            except requests.RequestException as e:
                last = e
                if attempt < retries - 1:
                    time.sleep(0.5 * (2**attempt))
        logger.warning(f"kv store unreachable after {retries} tries: {last}")
        raise last

    def _post(self, path: str, payload: dict, ok_statuses=(200,)):
        r = self._request("POST", path, json=payload)
        if r.status_code not in ok_statuses:
            r.raise_for_status()
        return r

    # ------------------------------ ABC --------------------------------

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        ttl = keepalive_ttl if keepalive_ttl else (
            self.ttl if delete_on_exit else None
        )
        r = self._post(
            "/kv/add",
            {"name": name, "value": str(value), "replace": replace,
             "ttl": ttl},
            ok_statuses=(200, 409),
        )
        if r.status_code == 409:
            raise NameEntryExistsError(name)
        with self._lock:
            if delete_on_exit:
                self._to_delete.append(name)
            if ttl:
                self._leased.append(name)
                self._ensure_keepalive()

    def get(self, name):
        r = self._request(
            "GET", "/kv/get", params={"name": name.rstrip("/")}
        )
        if r.status_code == 404:
            raise NameEntryNotFoundError(name)
        r.raise_for_status()
        return r.json()["value"]

    def _keys(self, name_root):
        r = self._request(
            "GET", "/kv/keys", params={"root": name_root.rstrip("/")}
        )
        r.raise_for_status()
        return r.json()

    def get_subtree(self, name_root):
        return self._keys(name_root)["values"]

    def find_subtree(self, name_root):
        return self._keys(name_root)["keys"]

    def delete(self, name):
        r = self._post(
            "/kv/delete", {"name": name.rstrip("/")}, ok_statuses=(200, 404)
        )
        if r.status_code == 404:
            raise NameEntryNotFoundError(name)
        with self._lock:
            if name in self._leased:
                self._leased.remove(name)

    def clear_subtree(self, name_root):
        self._post("/kv/clear", {"root": name_root})

    def reset(self):
        self._stop.set()
        with self._lock:
            names, self._to_delete = self._to_delete, []
            self._leased = []
        for name in names:
            try:
                self.delete(name)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    # --------------------------- keepalive -----------------------------

    def _ensure_keepalive(self):
        if self._keepalive is None or not self._keepalive.is_alive():
            self._stop.clear()
            self._keepalive = threading.Thread(
                target=self._keepalive_loop, daemon=True
            )
            self._keepalive.start()

    def _keepalive_loop(self):
        interval = max(0.2, self.ttl / 3)
        while not self._stop.wait(interval):
            with self._lock:
                names = list(self._leased)
            if not names:
                continue
            try:
                self._post("/kv/touch", {"names": names, "ttl": self.ttl})
            except Exception as e:  # noqa: BLE001 — server blip: keys may
                # expire, peers will see this process as dead (intended)
                logger.warning(f"kv keepalive failed: {e}")



def main():
    from aiohttp import web

    p = argparse.ArgumentParser()
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=18999)
    p.add_argument("--sweep-interval", type=float, default=1.0)
    args = p.parse_args()
    server = KVServer(sweep_interval=args.sweep_interval)
    logger.info(f"kv store on {args.host}:{args.port}")
    web.run_app(server.app(), host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
