"""Multi-process (multi-host) runtime plumbing.

TPU-native counterpart of the reference's process-group bring-up and
cross-rank data movement:

- `init_distributed` plays the role of torch `init_process_group` +
  platform backend selection (areal/engine/fsdp_engine.py:112
  create_process_group, areal/platforms/*.communication_backend): one
  `jax.distributed.initialize` call and every chip on every host joins a
  single global device list; GSPMD collectives ride ICI within a slice and
  DCN across hosts with no further group bookkeeping.
- `broadcast_pytree` is the host-side data plane the reference builds from
  NCCL broadcast + two-phase shape handshakes (areal/utils/data.py:915-1007
  broadcast_tensor_container, core/dist_rollout.py:99-146): arbitrary
  pytrees move head -> all via two device broadcasts (length, payload).
- `make_global_batch` turns a replicated host batch into jax Arrays laid
  out over a multi-process mesh (the role of DTensor construction under
  FSDP2): each process contributes exactly the shards it owns.

Env contract (set by the launcher, one process per host):
  AREAL_COORDINATOR   host:port of process 0 (jax.distributed coordinator)
  AREAL_NUM_PROCESSES total process count
  AREAL_PROCESS_ID    this process's rank
"""

import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.utils import logging

logger = logging.getLogger("distributed")

_INITIALIZED = False


def multi_process_requested() -> bool:
    return int(os.environ.get("AREAL_NUM_PROCESSES", "1")) > 1


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the global JAX runtime.  No-op when single-process (the common
    dev path) or when already initialized.  Arguments default to the
    AREAL_* env contract above."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    num_processes = num_processes or int(os.environ.get("AREAL_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    coordinator = coordinator or os.environ["AREAL_COORDINATOR"]
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ["AREAL_PROCESS_ID"])
    )
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
        # XLA:CPU has no cross-process collectives of its own ("Multiprocess
        # computations aren't implemented on the CPU backend"); the gloo
        # TCP backend provides them.  Must be configured BEFORE the backend
        # initializes — and only for explicit CPU runs (the multi-process
        # CPU tests): TPU runs use ICI/DCN and must not see this.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older/newer jax without the knob
            logger.warning(
                "could not select gloo CPU collectives; multi-process CPU "
                "collectives may be unavailable"
            )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _INITIALIZED = True
    logger.info(
        f"joined distributed runtime: process {process_id}/{num_processes}, "
        f"{len(jax.local_devices())} local / {len(jax.devices())} global devices"
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_head() -> bool:
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# Host-side data plane
# ---------------------------------------------------------------------------


def broadcast_pytree(obj: Any, is_source: Optional[bool] = None) -> Any:
    """Broadcast an arbitrary picklable pytree from the head process to all.

    Two-phase (length then payload) because `broadcast_one_to_all` needs
    identical shapes on every process and only the head knows the batch's
    — the same reason the reference's tensor-container broadcast sends
    metadata before data (areal/utils/data.py:948-1007).
    """
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return obj
    if is_source is None:
        is_source = is_head()
    payload = (
        np.frombuffer(pickle.dumps(obj), np.uint8)
        if is_source
        else np.zeros((0,), np.uint8)
    )
    n = multihost_utils.broadcast_one_to_all(
        np.array([payload.size], np.int64), is_source=is_source
    )
    buf = np.zeros((int(n[0]),), np.uint8)
    if is_source:
        buf[:] = payload
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    # broadcast_one_to_all implements the broadcast as a psum behind a
    # source flag, which PROMOTES the dtype on some backends (uint8 ->
    # float); the values stay exact (<= 255) but bytes() of the promoted
    # buffer would reinterpret float words as pickle opcodes — cast back
    # before decoding
    return pickle.loads(np.asarray(buf).astype(np.uint8).tobytes())


def make_global_batch(
    mesh: Mesh, spec_for: Dict[str, P], host_batch: Dict[str, np.ndarray]
) -> Dict[str, jax.Array]:
    """Replicated host batch -> global device arrays over a (possibly
    multi-process) mesh.  Every process must hold the identical host batch
    (use `broadcast_pytree` first); each contributes its local shards."""
    out = {}
    for k, v in host_batch.items():
        sharding = NamedSharding(mesh, spec_for[k])
        out[k] = jax.make_array_from_callback(
            v.shape, sharding, lambda idx, v=v: v[idx]
        )
    return out


def fetch_replicated(tree: Any) -> Any:
    """device_get for outputs that are replicated over the mesh (stats,
    losses): safe in multi-process because every process holds a full
    replica as an addressable shard.  All leaves go through ONE batched
    device_get (async copies issued together) — per-leaf np.asarray would
    pay a blocking round-trip each, which dominates on tunneled runtimes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    local = [
        x.addressable_data(0) if isinstance(x, jax.Array) else x for x in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, jax.device_get(local))
