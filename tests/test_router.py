"""Router service tests against fake backend servers over real HTTP
(the reference tests GserverManager the same way,
realhf/tests/system/test_gserver_manager.py:38)."""

import asyncio
import threading

import pytest
from aiohttp import web

from areal_tpu.gen.router import Router, RouterConfig
from areal_tpu.utils import name_resolve, names

from tests.fake_server import FakeGenServer


class RouterHarness:
    """Runs the router app on a background loop like FakeGenServer does."""

    def __init__(self, router: Router):
        self.router = router
        self.port = None
        self._loop = None
        self._runner = None
        self._thread = None
        self._started = threading.Event()

    def start(self) -> str:
        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                runner = web.AppRunner(self.router.app())
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                self.port = runner.addresses[0][1]
                self._runner = runner
                self._started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        assert self._started.wait(timeout=10)
        return f"127.0.0.1:{self.port}"

    def stop(self):
        async def _cleanup():
            await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(_cleanup(), self._loop).result(timeout=5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


@pytest.fixture()
def fleet():
    servers = [FakeGenServer(completion=list(range(100, 104))) for _ in range(3)]
    addrs = [s.start() for s in servers]
    yield servers, addrs
    for s in servers:
        s.stop()


def _post(addr, endpoint, payload, expect_status=200):
    import json
    import urllib.request

    req = urllib.request.Request(
        f"http://{addr}{endpoint}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = json.loads(e.read() or b"{}")
        assert e.code == expect_status, (e.code, body)
        return e.code, body


def _get(addr, endpoint):
    import json
    import urllib.request

    with urllib.request.urlopen(f"http://{addr}{endpoint}", timeout=30) as resp:
        return json.loads(resp.read())


def test_routing_policies_and_affinity(fleet):
    servers, addrs = fleet
    router = Router(RouterConfig(schedule_policy="round_robin"), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # distinct rids round-robin across backends
        for i in range(6):
            status, out = _post(
                raddr,
                "/generate",
                {
                    "rid": f"r{i}",
                    "input_ids": [1, 2, 3],
                    "sampling_params": {"max_new_tokens": 16},
                },
            )
            assert status == 200 and out["output_tokens"]
        counts = [len(s.requests) for s in servers]
        assert counts == [2, 2, 2], counts

        # same rid sticks to one backend (KV affinity)
        for _ in range(3):
            _post(
                raddr,
                "/generate",
                {
                    "rid": "sticky",
                    "input_ids": [5],
                    "sampling_params": {"max_new_tokens": 16},
                },
            )
        counts2 = [len(s.requests) - c for s, c in zip(servers, counts)]
        assert sorted(counts2) == [0, 0, 3], counts2

        metrics = _get(raddr, "/metrics")
        assert sum(metrics["requests_routed"].values()) == 9
        # _tokens is live in-flight load: freed once requests complete
        assert all(v == 0 for v in metrics["tokens_inflight"].values())
    finally:
        h.stop()


def test_least_tokens_balances_heterogeneous_load():
    """VERDICT r3 weak #7: drive least_tokens under heterogeneous-length
    load.  One huge prompt occupies backend A; while it is in flight, many
    small prompts must ALL go to backend B (token load stays balanced),
    whereas least_requests would alternate and stack half the small
    requests behind the giant (reference least_token_usage policy,
    gserver_manager.py:175-191)."""
    import concurrent.futures
    import time as _time

    servers = [FakeGenServer(completion=[7], chunk_size=1) for _ in range(2)]
    addrs = [s.start() for s in servers]
    for s in servers:
        s.delay_s = 4.0  # keep requests in flight so load is observable
    router = Router(
        RouterConfig(schedule_policy="least_tokens"), addresses=addrs
    )
    h = RouterHarness(router)
    raddr = h.start()
    try:
        pool = concurrent.futures.ThreadPoolExecutor(max_workers=12)

        def gen(rid, n_tokens):
            return _post(raddr, "/generate", {
                "rid": rid,
                "input_ids": list(range(n_tokens)),
                "sampling_params": {"max_new_tokens": 1},
            })

        futs = [pool.submit(gen, "giant", 1000)]
        # let the giant land first so its token weight is visible
        deadline = _time.monotonic() + 5
        while (_time.monotonic() < deadline
               and sum(len(s.requests) for s in servers) < 1):
            _time.sleep(0.01)
        futs += [pool.submit(gen, f"small-{i}", 10) for i in range(10)]
        for f in futs:
            status, out = f.result(timeout=30)
            assert status == 200 and out["output_tokens"]

        giant_srv = next(
            i for i, s in enumerate(servers)
            if any(len(r["input_ids"]) == 1000 for r in s.requests)
        )
        other_srv = 1 - giant_srv
        # small requests avoided the token-loaded backend: 10 x 10 tokens
        # never catch up to the giant's 1000.  Bound, not exact equality —
        # on a loaded machine a straggler can route after the giant
        # completes and its token weight drops to zero.
        n_small_other = len(servers[other_srv].requests)
        n_on_giant = len(servers[giant_srv].requests) - 1
        assert n_small_other >= 8, (n_small_other, n_on_giant)
        # request COUNT is heavily skewed — least_requests would have
        # split these ~6/5; tokens, the gated resource, stayed balanced
        metrics = _get(raddr, "/metrics")
        assert all(v == 0 for v in metrics["tokens_inflight"].values())
        pool.shutdown(wait=True)
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_global_staleness_gate(fleet):
    _, addrs = fleet
    cfg = RouterConfig(
        train_batch_size=2, max_head_offpolicyness=0, schedule_policy="round_robin"
    )
    router = Router(cfg, addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # version 0: (0 + 0 + 1) * 2 = 2 admissions allowed fleet-wide
        s1, r1 = _post(raddr, "/allocate_request", {"qid": "a"})
        s2, r2 = _post(raddr, "/allocate_request", {"qid": "b"})
        assert s1 == s2 == 200 and not r1["staled"] and not r2["staled"]
        s3, r3 = _post(raddr, "/allocate_request", {"qid": "c"}, expect_status=409)
        assert s3 == 409 and r3["staled"]

        # finishing without acceptance frees capacity
        _post(raddr, "/finish_request", {"qid": "a", "accepted": False})
        s4, _ = _post(raddr, "/allocate_request", {"qid": "c"})
        assert s4 == 200

        # accepted samples keep counting against the budget
        _post(raddr, "/finish_request", {"qid": "b", "accepted": True})
        s5, _ = _post(raddr, "/allocate_request", {"qid": "d"}, expect_status=409)
        assert s5 == 409
    finally:
        h.stop()


def test_manual_weight_update_flushes_fleet(fleet):
    servers, addrs = fleet
    router = Router(RouterConfig(), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        status, out = _post(
            raddr, "/update_weights", {"path": "/dev/null/v7", "version": 7}
        )
        assert status == 200 and out["version"] == 7
        for s in servers:
            assert len(s.weight_updates) == 1
            assert s.weight_updates[0]["path"] == "/dev/null/v7"
            assert s.paused is False  # resumed after the flush
        health = _get(raddr, "/health")
        assert health["version"] == 7

        # gate capacity grows with version: (0 + 7 + 1) * bs
        router.config.train_batch_size = 1
        for i in range(8):
            s, _ = _post(raddr, "/allocate_request", {"qid": f"q{i}"})
            assert s == 200
        s, _ = _post(raddr, "/allocate_request", {"qid": "overflow"}, expect_status=409)
        assert s == 409
    finally:
        h.stop()


def test_checkpoint_watcher_picks_up_trainer_publishes(fleet, tmp_path):
    servers, addrs = fleet
    cfg = RouterConfig(
        experiment_name="rtest",
        trial_name="t0",
        weights_path=str(tmp_path),
        poll_interval=0.05,
    )
    router = Router(cfg, addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # trainer publishes version 3 (key layout from JaxTrainEngine.update_weights)
        name_resolve.add(
            names.update_weights_from_disk("rtest", "t0", 3), "123", replace=True
        )
        import time

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and router.version < 3:
            time.sleep(0.05)
        assert router.version == 3
        for s in servers:
            assert s.weight_updates and s.weight_updates[-1]["path"].endswith("/v3")
    finally:
        h.stop()


def test_transfer_mode_version_sources_unwedge_gate(fleet):
    """ADVICE r3 (medium): in a transfer-mode fleet (no disk checkpoints,
    trainer pushes chunks straight to servers) the router's gate version
    must still advance — via POST /set_version from the train loop, or the
    backend /health version poll — or admission wedges at 409 forever."""
    import time as _time

    servers, addrs = fleet
    cfg = RouterConfig(
        train_batch_size=1,
        max_head_offpolicyness=0,
        version_poll_interval=0.05,  # no weights_path -> poller active
    )
    router = Router(cfg, addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # budget (0 + 0 + 1) * 1 = 1: second admission is staleness-bound
        s, r = _post(raddr, "/allocate_request", {"qid": "a"})
        assert s == 200
        _post(raddr, "/finish_request", {"alloc_id": r["alloc_id"],
                                         "accepted": True})
        s, _ = _post(raddr, "/allocate_request", {"qid": "b"},
                     expect_status=409)
        assert s == 409

        # source 1: the trainer's explicit /set_version (jax_train.py
        # _notify_router after a transfer commit)
        s, out = _post(raddr, "/set_version", {"version": 1})
        assert s == 200 and out["version"] == 1
        s, _ = _post(raddr, "/allocate_request", {"qid": "b"})
        assert s == 200

        # source 2: the backend health poll — a transfer commit bumps each
        # server's served version even when nobody calls /set_version
        for srv in servers:
            srv.version = 5
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline and router.version < 5:
            _time.sleep(0.05)
        assert router.version == 5
    finally:
        h.stop()


def test_fleet_gate_two_clients_share_one_budget(fleet, monkeypatch):
    """VERDICT r2 #2: N clients against one fleet must share ONE staleness
    budget (reference is_staled, gserver_manager.py:334).  Two RemoteJaxEngine
    clients with permissive LOCAL staleness run against a router whose gate
    allows 4 admissions at v0; the fleet must admit exactly 4 episodes total
    until a weight update raises the version."""
    import threading as _threading
    import time as _time

    import numpy as np
    from areal_tpu.api.config import (
        GenerationHyperparameters,
        InferenceEngineConfig,
    )
    from areal_tpu.api.workflow import RolloutWorkflow
    from areal_tpu.engine.jax_remote import RemoteJaxEngine

    servers, addrs = fleet
    router = Router(
        RouterConfig(
            train_batch_size=2,  # (eta=0 + v + 1) * 2 -> 4 admissions at v=1
            max_head_offpolicyness=0,
            schedule_policy="round_robin",
        ),
        addresses=addrs,
    )
    router.version = 1
    h = RouterHarness(router)
    raddr = h.start()
    monkeypatch.setenv("AREAL_GEN_ROUTER_ADDR", raddr)

    class _W(RolloutWorkflow):
        async def arun_episode(self, engine, data):
            from areal_tpu.api.io_struct import ModelRequest

            resp = await engine.agenerate(ModelRequest(
                rid=str(data["query_id"]),
                input_ids=[1, 2, 3],
                gconfig=GenerationHyperparameters(max_new_tokens=8),
            ))
            ids = [1, 2, 3] + resp.output_tokens
            return {
                "input_ids": np.array([ids], np.int32),
                "attention_mask": np.ones((1, len(ids)), bool),
            }

    clients = []
    for i in range(2):
        c = RemoteJaxEngine(InferenceEngineConfig(
            experiment_name="fg", trial_name=f"c{i}", consumer_batch_size=4,
            max_concurrent_rollouts=16, max_head_offpolicyness=100,
            request_timeout=10,
        ))
        c.initialize(addr=raddr)  # generation also proxies through the router
        assert c.executor.fleet_gate is not None
        # fast poll so the post-update drain happens within test time
        c.executor.fleet_gate.poll_interval = 0.1
        clients.append(c)

    results = {}

    def _run(idx):
        results[idx] = clients[idx].rollout_batch(
            [{"query_id": f"{idx}-{j}"} for j in range(4)], workflow=_W()
        )

    threads = [_threading.Thread(target=_run, args=(i,)) for i in range(2)]
    try:
        for t in threads:
            t.start()
        # steady state before the weight update: exactly 4 admissions
        # fleet-wide (accepted + running), the other 4 episodes blocked
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            with_lease = router._accepted + len(router._running)
            if router._accepted >= 4:
                break
            _time.sleep(0.05)
        _time.sleep(0.5)  # would-be overshoot window
        assert router._accepted + len(router._running) <= 4
        assert router._accepted == 4

        # weight update -> version 3 -> budget (0+3+1)*2 = 8: all drain
        _post(raddr, "/update_weights", {"path": "/dev/null/v3", "version": 3})
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        assert results[0]["input_ids"].shape[0] == 4
        assert results[1]["input_ids"].shape[0] == 4
        assert router._accepted == 8
    finally:
        for c in clients:
            c.destroy()
        h.stop()


def test_lease_ttl_expiry_replaces_and_rejects_late_finish(fleet):
    """ISSUE 11 satellite: a lease past alloc_ttl is reclaimed (its slot
    re-placeable) and the original client's LATE /finish_request is
    rejected as expired — counting it would double-book the admission
    budget against whoever now holds the slot."""
    import time as _time

    _, addrs = fleet
    cfg = RouterConfig(
        train_batch_size=1, max_head_offpolicyness=0, alloc_ttl=0.2
    )
    router = Router(cfg, addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # budget (0 + 0 + 1) * 1 = 1: one admission fleet-wide
        s, r1 = _post(raddr, "/allocate_request", {"qid": "a"})
        assert s == 200 and r1["alloc_id"]
        s, _ = _post(raddr, "/allocate_request", {"qid": "b"},
                     expect_status=409)
        assert s == 409

        _time.sleep(0.3)  # client "a" stalls past the TTL
        s, r2 = _post(raddr, "/allocate_request", {"qid": "b"})
        assert s == 200, "expired lease must be re-placeable"

        # the stalled client finally answers: rejected, not double-counted
        s, out = _post(raddr, "/finish_request",
                       {"alloc_id": r1["alloc_id"], "accepted": True})
        assert s == 200 and out == {"ok": False, "expired": True}
        assert router._accepted == 0

        s, out = _post(raddr, "/finish_request",
                       {"alloc_id": r2["alloc_id"], "accepted": True})
        assert s == 200 and out["ok"]
        assert router._accepted == 1
    finally:
        h.stop()


def test_health_cached_with_freshness_and_breaker_detection():
    """ISSUE 11 satellite: /health serves the checker's CACHED state with a
    freshness timestamp (no inline probe fanout per scrape), and the active
    probe loop trips a dead backend open within
    ~failure_threshold * interval."""
    import time as _time

    servers = [FakeGenServer(completion=[100, 101]) for _ in range(2)]
    addrs = [s.start() for s in servers]
    router = Router(
        RouterConfig(
            health_check_interval=0.1,
            health_failure_threshold=2,
            health_probe_timeout=0.5,
        ),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    try:
        health = _get(raddr, "/health")
        assert health["status"] == "ok"
        assert set(health["servers"]) == set(addrs)
        assert all(s["state"] == "closed" for s in health["servers"].values())
        assert health["freshness_s"] is not None

        servers[0].stop()
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            try:
                _get(raddr, "/health")
            except Exception:  # 503 once degraded
                break
            _time.sleep(0.05)
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get(raddr, "/health")
        body = exc_info.value.read().decode()
        import json as _json

        health = _json.loads(body)
        assert health["status"] == "degraded"
        assert health["servers"][addrs[0]]["state"] == "open"
        assert health["servers"][addrs[1]]["state"] == "closed"
        # the cached view is fresh: the probe loop runs every 0.1s
        assert health["freshness_s"] < 5.0

        # new placements avoid the open backend entirely
        for i in range(4):
            s, out = _post(raddr, "/generate", {
                "rid": f"post-death-{i}", "input_ids": [1],
                "sampling_params": {"max_new_tokens": 4},
            })
            assert s == 200 and out["output_tokens"]
        assert len(servers[0].requests) == 0

        # the JSON metrics surface mirrors the breaker view (the Prometheus
        # exposition of areal_router_backend_state is asserted in
        # test_telemetry.py, which owns the shared ROUTER registry)
        m = _get(raddr, "/metrics")
        assert m["backend_states"][addrs[0]]["state"] == "open"
    finally:
        h.stop()
        servers[1].stop()


def test_drain_excludes_placement_but_keeps_fanout(fleet):
    """Draining is graceful removal: no NEW placements, but the backend
    still receives control-plane fanouts (final weight sync completes)."""
    servers, addrs = fleet
    router = Router(
        RouterConfig(schedule_policy="round_robin", health_check_interval=0),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    try:
        s, _ = _post(raddr, "/drain", {"addr": addrs[0]})
        assert s == 200
        s, _ = _post(raddr, "/drain", {"addr": "10.0.0.1:1"},
                     expect_status=404)
        assert s == 404

        for i in range(6):
            s, out = _post(raddr, "/generate", {
                "rid": f"r{i}", "input_ids": [1],
                "sampling_params": {"max_new_tokens": 4},
            })
            assert s == 200 and out["output_tokens"]
        counts = [len(s.requests) for s in servers]
        assert counts == [0, 3, 3], counts

        # fanouts still reach the draining backend
        s, _ = _post(raddr, "/update_weights", {"path": "/dev/null/v1",
                                                "version": 1})
        assert s == 200
        assert all(len(s.weight_updates) == 1 for s in servers)

        s, _ = _post(raddr, "/undrain", {"addr": addrs[0]})
        assert s == 200
        _post(raddr, "/generate", {
            "rid": "back", "input_ids": [1],
            "sampling_params": {"max_new_tokens": 4},
        })
        assert len(servers[0].requests) == 1
    finally:
        h.stop()


# ---------------------------------------------------------------------------
# disaggregated prefill/decode serving (ISSUE 17)
# ---------------------------------------------------------------------------


def _disagg_fleet(roles, completion=None):
    from tests.fake_server import FakeGenServer as _F

    servers = [
        _F(completion=list(completion or range(100, 108)), role=r)
        for r in roles
    ]
    return servers, [s.start() for s in servers]


def test_disagg_two_leg_handoff_merges_stream():
    """Happy path: leg 1 (one token) on the prefill server, /kv_export ->
    /kv_import, leg 2 on ONE decode server with the pinned stream id,
    and the merged response carries the full token stream."""
    servers, addrs = _disagg_fleet(["prefill", "decode", "decode"])
    router = Router(RouterConfig(disagg=True), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        status, out = _post(raddr, "/generate", {
            "rid": "d0", "input_ids": [1, 2, 3], "stream_id": 77,
            "sampling_params": {"max_new_tokens": 8},
        })
        assert status == 200
        assert out["output_tokens"] == list(range(100, 108))
        assert len(out["output_logprobs"]) == 8
        assert out["handoff"] is True
        prefill, d1, d2 = servers
        assert len(prefill.requests) == 1
        assert prefill.requests[0]["sampling_params"]["max_new_tokens"] == 1
        assert prefill.requests[0]["stream_id"] == 77
        assert len(prefill.kv_exports) == 1
        assert prefill.kv_exports[0]["input_ids"] == [1, 2, 3, 100]
        leg2 = [r for s in (d1, d2) for r in s.requests]
        assert len(leg2) == 1
        assert leg2[0]["input_ids"] == [1, 2, 3, 100]
        assert leg2[0]["stream_id"] == 77
        assert leg2[0]["sampling_params"]["max_new_tokens"] == 7
        assert sum(len(s.kv_imports) for s in (d1, d2)) == 1
        m = _get(raddr, "/metrics")
        assert m["handoffs"] == 1 and m["handoff_fallbacks"] == 0
        assert m["roles"][addrs[0]] == "prefill"
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_disagg_empty_role_pool_falls_back_colocated():
    """`both` servers stay OUT of the role pools: with no prefill/decode
    split available the router serves the request colocated in one leg."""
    servers, addrs = _disagg_fleet(["both", "both"])
    router = Router(RouterConfig(disagg=True), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        status, out = _post(raddr, "/generate", {
            "rid": "c0", "input_ids": [1, 2],
            "sampling_params": {"max_new_tokens": 8},
        })
        assert status == 200
        assert out["output_tokens"] == list(range(100, 108))
        assert "handoff" not in out
        reqs = [r for s in servers for r in s.requests]
        assert len(reqs) == 1  # one leg, no clipping
        assert reqs[0]["sampling_params"]["max_new_tokens"] == 8
        assert not any(s.kv_exports or s.kv_imports for s in servers)
        assert _get(raddr, "/metrics")["handoffs"] == 0
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_disagg_import_failure_continues_on_prefill():
    """A failed transfer (dead/refusing decode import) must not lose the
    stream: leg 2 runs on the prefill server itself — exact under the
    counter-keyed sampler — and counts a handoff fallback."""
    from areal_tpu.utils.faults import Fault, FaultPlan

    servers, addrs = _disagg_fleet(["prefill", "decode"])
    servers[1].fault_plan = FaultPlan({("/kv_import", 0): Fault("http_500")})
    router = Router(RouterConfig(disagg=True), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        status, out = _post(raddr, "/generate", {
            "rid": "f0", "input_ids": [4, 5, 6],
            "sampling_params": {"max_new_tokens": 8},
        })
        assert status == 200
        assert out["output_tokens"] == list(range(100, 108))
        assert out["handoff"] is False
        prefill, decode = servers
        # leg 1 AND the fallback leg 2 both landed on the prefill server
        assert len(prefill.requests) == 2
        assert len(decode.requests) == 0
        m = _get(raddr, "/metrics")
        assert m["handoffs"] == 0 and m["handoff_fallbacks"] == 1
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_disagg_finished_in_leg1_skips_transfer():
    """EOS inside leg 1 (a one-token completion): nothing to hand off —
    the leg-1 response is returned directly and no transfer happens."""
    servers, addrs = _disagg_fleet(["prefill", "decode"], completion=[42])
    router = Router(RouterConfig(disagg=True), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        status, out = _post(raddr, "/generate", {
            "rid": "e0", "input_ids": [9],
            "sampling_params": {"max_new_tokens": 8},
        })
        assert status == 200
        assert out["output_tokens"] == [42]
        assert out["stop_reason"] == "stop"
        assert not servers[0].kv_exports and not servers[1].kv_imports
        assert len(servers[1].requests) == 0
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_disagg_group_affinity_sticks_to_one_prefill():
    """GRPO fan-out: group members must land on ONE prefill server (the
    shared-prefix fan-out only works inside a single engine's cache)."""
    servers, addrs = _disagg_fleet(["prefill", "prefill", "decode"])
    router = Router(RouterConfig(disagg=True), addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        for i in range(4):
            status, _ = _post(raddr, "/generate", {
                "rid": f"g0-{i}", "group_id": "g0", "input_ids": [1, 2],
                "sampling_params": {"max_new_tokens": 8},
            })
            assert status == 200
        leg1_counts = sorted(len(s.requests) for s in servers[:2])
        assert leg1_counts == [0, 4], leg1_counts
    finally:
        h.stop()
        for s in servers:
            s.stop()


def test_disagg_decode_pick_prefers_low_occupancy():
    """Decode placement keys on tier occupancy from /metrics: a full
    decode server loses placement to an idle one once the poller has a
    sample."""
    import time as _time

    servers, addrs = _disagg_fleet(["prefill", "decode", "decode"])
    servers[1].tier_occupancy, servers[1].tier_slots = [8], [8]  # full
    servers[2].tier_occupancy, servers[2].tier_slots = [0], [8]  # idle
    router = Router(RouterConfig(disagg=True, occupancy_poll_interval=0.1),
                    addresses=addrs)
    h = RouterHarness(router)
    raddr = h.start()
    try:
        _time.sleep(0.6)  # let the occupancy poller sample both servers
        for i in range(3):
            status, _ = _post(raddr, "/generate", {
                "rid": f"o{i}", "input_ids": [1, 2, 3],
                "sampling_params": {"max_new_tokens": 8},
            })
            assert status == 200
        assert len(servers[2].requests) == 3  # all tails on the idle one
        assert len(servers[1].requests) == 0
    finally:
        h.stop()
        for s in servers:
            s.stop()
