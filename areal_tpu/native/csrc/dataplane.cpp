// Native data-plane kernels for the host-side hot path.
//
// TPU-native counterpart of the reference's csrc/ extensions.  The
// reference's CUDA interval-copy kernels (csrc/interval_op/interval_op.cu)
// serve its flattened-param reallocation, which this design removes (GSPMD
// resharding replaces live param realloc); its bin-packing runs in Python
// (areal/utils/datapack.py ffd_allocate).  What remains hot on the HOST
// here is the per-batch bin-packing (FFD / LPT) in the rollout->train
// handoff.  Compiled with g++ -O3 -shared -fPIC, loaded via ctypes
// (areal_tpu/native/__init__.py); every entry point has a pure-Python
// fallback with identical semantics (parity-tested).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// First-fit-decreasing bin packing.  Items sorted by decreasing size
// (stable: ties keep index order) are placed into the first existing bin
// with room, else a new bin.  Returns the bin count; bin_of[i] = bin of
// item i.  Items larger than capacity get singleton bins (first-fit finds
// no room, matching the Python reference semantics).
int64_t ffd_assign(const int64_t* sizes, int64_t n, int64_t capacity,
                   int32_t* bin_of) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return sizes[a] > sizes[b]; });
  std::vector<int64_t> loads;
  loads.reserve(64);
  for (int64_t k = 0; k < n; ++k) {
    const int64_t idx = order[k];
    const int64_t size = sizes[idx];
    int64_t placed = -1;
    for (size_t b = 0; b < loads.size(); ++b) {
      if (loads[b] + size <= capacity) {
        placed = static_cast<int64_t>(b);
        break;
      }
    }
    if (placed < 0) {
      placed = static_cast<int64_t>(loads.size());
      loads.push_back(0);
    }
    loads[placed] += size;
    bin_of[idx] = static_cast<int32_t>(placed);
  }
  return static_cast<int64_t>(loads.size());
}

// Longest-processing-time balanced partition into exactly k groups:
// descending sizes, each item to the currently lightest group (ties ->
// lowest group index, matching numpy argmin).
void lpt_assign(const int64_t* sizes, int64_t n, int64_t k,
                int32_t* group_of) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return sizes[a] > sizes[b]; });
  std::vector<int64_t> loads(k, 0);
  for (int64_t t = 0; t < n; ++t) {
    const int64_t idx = order[t];
    int64_t best = 0;
    for (int64_t g = 1; g < k; ++g) {
      if (loads[g] < loads[best]) best = g;
    }
    loads[best] += sizes[idx];
    group_of[idx] = static_cast<int32_t>(best);
  }
}

}  // extern "C"
