"""areal-lint: project-specific static analysis (ISSUE 3 + 9 + 18).

Ten checkers tuned to this codebase's invariants, plus an opt-in
runtime validator for the lock annotations:

- C1 `unlocked-field`   (lock_discipline)  — guarded fields under locks
- C2 `host-sync` family (host_sync)        — hot-path device fences and
  recompile hazards
- C3 `async-blocking`   (async_blocking)   — event-loop stalls
- C4 `dead-module`      (dead_modules)     — unreachable package code
- C5 `lock-order` family (lock_order)      — interprocedural deadlock /
  blocking-under-lock / atomicity-split analysis over the call graph
- C6 `off-ladder-static` (jit_signatures)  — jit static-arg ladder proof
  + checked-in per-function signature budgets
- C7 `slot-*` typestate  (typestate)       — slot/cache-row lifecycle
- C8 `payload-contract` family (wire_contracts) — HTTP producer/consumer
  key-sets vs the checked-in endpoint registry, both directions, incl.
  silent `.get`-default reads of always-produced keys
- C9 `metric-contract`/`event-contract` (wire_contracts) — every
  telemetry metric pinned in tests/data/metrics_schema.json and every
  emitted event consumed by obs/trace.py, bidirectionally (no orphans)
- C10 `config-plumbing` (wire_contracts)   — GenServerConfig field →
  build_cmd flag → gen/server.py argparse → engine kwarg, end-to-end

C5–C7 share the interprocedural substrate in callgraph.py (class/lock
index, call resolution, summary fixpoint).  C8–C10 share the wire
registry areal_tpu/analysis/wire_contracts.json (`wire-registry-stale`
flags entries the code no longer backs).

CLI: ``python scripts/lint.py --check`` (the tier-1 gate runs the same
suite via tests/test_lint.py::test_repo_clean).  Catalog, annotation and
suppression syntax: docs/lint.md.
"""

from areal_tpu.analysis.callgraph import CallGraph, fixpoint
from areal_tpu.analysis.core import (
    KNOWN_RULES,
    Finding,
    SourceFile,
    load_files,
    run_suite,
    suppression_hygiene,
    unsuppressed,
)
from areal_tpu.analysis.jit_signatures import (
    budget_drift,
    compute_budgets,
    render_budget_doc,
)
from areal_tpu.analysis.lockcheck import (
    LockDisciplineError,
    debug_locks_enabled,
    lock_guarded,
)

__all__ = [
    "KNOWN_RULES",
    "CallGraph",
    "Finding",
    "SourceFile",
    "fixpoint",
    "load_files",
    "run_suite",
    "suppression_hygiene",
    "unsuppressed",
    "budget_drift",
    "compute_budgets",
    "render_budget_doc",
    "LockDisciplineError",
    "debug_locks_enabled",
    "lock_guarded",
]
