"""Math answer extraction and verification.

Behavioral counterpart of the reference's rule-based math verifier
(areal/reward/math_parser.py:219 strip_string, :360 extract_answer, :495
math_equal, backed by vendored latex2sympy in evaluation/): extract the
model's final answer, normalise latex/number/unit formatting, and compare —
string match, then numeric (with the reference's percentage tolerance),
then element-wise for tuples/intervals/matrices, then sympy symbolic
equivalence.  antlr/latex2sympy is not available in this image, so latex is
lowered to sympy-parsable text by an in-repo rewriter instead of a vendored
grammar.

Reward honesty (round-1 review weak #6): `extract_answer` used as a REWARD
signal is strict — it requires an explicit answer marker (\\boxed{},
"the answer is", "####", "$ ... $. I hope") and returns None otherwise.
The permissive last-number fallback the reference enables for offline eval
(`use_last_number=True`) exists behind `strict=False` only; RL reward
functions never use it, so emitting any number cannot farm reward.

Runs inside the reward process pool (api/reward.py), so sympy hangs are
bounded by the pool timeout rather than an in-process alarm.
"""

import re
from typing import List, Optional

# --------------------------------------------------------------------------
# extraction
# --------------------------------------------------------------------------


def _find_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} / \\fbox{...} content, brace-balanced."""
    idx = max(text.rfind("\\boxed"), text.rfind("\\fbox"))
    if idx < 0:
        return None
    brace = text.find("{", idx)
    if brace < 0:
        # \boxed 42 form
        m = re.match(r"\\boxed\s+(\S+)", text[idx:])
        return m.group(1) if m else None
    depth = 0
    for i in range(brace, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[brace + 1 : i]
    return None


_ANSWER_PATTERNS = [
    r"(?:final answer|the answer)\s*(?:is\s*:?|:)\s*([^\n]+)",
    r"####\s*([^\n]+)",
    # bare "Answer: 042" lines (AIME-style submissions)
    r"^answer\s*:\s*([^\n]+)",
    r"\nanswer\s*:\s*([^\n]+)",
]


def extract_answer(text: str, strict: bool = True) -> Optional[str]:
    """Pull the final answer out of a model completion.

    strict=True (reward path): only explicit answer markers count.
    strict=False (offline eval): additionally falls back to the last number
    in the text (reference extract_answer's use_last_number=True)."""
    if not text:
        return None
    # minerva-style "final answer is $X$. I hope it is correct."
    if "final answer is $" in text and "$. I hope" in text:
        frag = text.split("final answer is $", 1)[1].split("$. I hope", 1)[0]
        return frag.strip()
    boxed = _find_boxed(text)
    if boxed is not None:
        return boxed.strip()
    low = text.lower()
    for pat in _ANSWER_PATTERNS:
        matches = list(re.finditer(pat, low))
        if matches:
            m = matches[-1]
            ans = text[m.start(1) : m.end(1)].strip()
            # trim trailing prose after the expression: "is 42. Done" -> 42
            ans = re.split(r"(?<=[\d\w)\]}])\.\s", ans)[0]
            return ans.rstrip(".").strip()
    if not strict:
        nums = re.findall(r"-?\d[\d,]*(?:\.\d+)?", text)
        return nums[-1].replace(",", "") if nums else None
    return None


# --------------------------------------------------------------------------
# normalisation
# --------------------------------------------------------------------------

_WORD_NUMBERS = {
    "zero": "0", "one": "1", "two": "2", "three": "3", "four": "4",
    "five": "5", "six": "6", "seven": "7", "eight": "8", "nine": "9",
    "ten": "10", "eleven": "11", "twelve": "12",
}

# unit words stripped when attached to a number (reference strip_string's
# unit_texts table role); conservative: only straightforward count units
_UNIT_WORDS = [
    "degrees?", "dollars?", "cents?", "percent", "points?", "units?",
    "meters?", "metres?", "miles?", "feet", "foot", "inch(?:es)?",
    "centimeters?", "kilometers?", "km", "cm", "mm", "kg", "grams?",
    "pounds?", "ounces?", "liters?", "litres?", "ml",
    "seconds?", "minutes?", "hours?", "days?", "weeks?", "months?",
    "years?", "mph", "km/h", "sq", "square", "cubic", "per",
]
_UNIT_RE = re.compile(
    r"(?<=[\d\s.)])\s*\\?(?:" + "|".join(_UNIT_WORDS) + r")\b\.?", re.IGNORECASE
)

_LATEX_SUBS = [
    (r"\\left|\\right", ""),
    (r"\\!|\\,|\\;|\\:|\\ ", ""),
    (r"~", " "),
    (r"\\mathrm\{([^{}]*)\}", r"\1"),
    (r"\\mathbf\{([^{}]*)\}", r"\1"),
    (r"\\mbox\{[^{}]*\}$", ""),
    (r"\\mbox\{([^{}]*)\}", r"\1"),
    (r"\\\$|\$", ""),
    (r"\\%|%", ""),
    (r"\^\{?\\circ\}?", ""),
    (r"\\degree", ""),
    (r"\\dfrac|\\tfrac|\\cfrac", r"\\frac"),
    (r"\\cdot|\\times", "*"),
    (r"\\div", "/"),
    (r"\\pi\b", "pi"),
    (r"\\infty|infinity|\binf\b", "oo"),
    (r"\\ne(?:q)?\b", "!="),
    (r"\\le(?:q)?\b", "<="),
    (r"\\ge(?:q)?\b", ">="),
    (r"\\approx", "="),
    (r"\\begin\{array\}\{[^{}]*\}", r"\\begin{pmatrix}"),
    (r"\\end\{array\}", r"\\end{pmatrix}"),
    (r"bmatrix|vmatrix|Bmatrix", "pmatrix"),
    (r"\\in\b", "="),
]


def _fix_fracs(s: str) -> str:
    """All \\frac spellings -> ((a)/(b)): braced (one nesting level deep),
    half-braced (\\frac{a}b), and bare two-token (\\frac12, \\frac1x)
    forms.  Innermost fracs resolve first, so \\frac{\\frac{1}{2}}{3}
    converges over iterations."""
    token = r"(\{(?:[^{}]|\{[^{}]*\})*\}|[^\s{}\\])"
    pat = re.compile(r"\\frac\s*" + token + r"\s*" + token)
    for _ in range(10):  # bounded fixpoint
        m = pat.search(s)
        if not m:
            break
        num, den = (
            g[1:-1] if g.startswith("{") and g.endswith("}") else g
            for g in m.groups()
        )
        s = s[: m.start()] + f"(({num})/({den}))" + s[m.end() :]
    return s


def _fix_binom(s: str) -> str:
    """\\binom{n}{k} / \\dbinom -> binomial(n, k) (sympy-parseable)."""
    return re.sub(
        r"\\d?binom\s*\{([^{}]*)\}\s*\{([^{}]*)\}", r"binomial(\1,\2)", s
    )


def _fix_sqrt(s: str) -> str:
    s = re.sub(r"\\sqrt\s*\{([^{}]*)\}", r"sqrt(\1)", s)
    s = re.sub(r"\\sqrt\s*(\w)", r"sqrt(\1)", s)
    return s


def _fix_mixed_number(s: str) -> str:
    """3\\frac{1}{2} and '3 1/2' style mixed numbers -> (3+(1)/(2))."""
    m = re.fullmatch(r"(-?\d+)\s*\(\((\d+)\)/\((\d+)\)\)", s)
    if m:
        whole, num, den = m.groups()
        sign = "-" if whole.startswith("-") else "+"
        return f"({whole}{sign}({num})/({den}))"
    return s


def normalize_answer(ans: str) -> str:
    s = str(ans).strip().replace("\n", "")
    s = s.rstrip(".").rstrip("/")
    s = re.sub(r"\\text\s*\{([^{}]*)\}", r"\1", s)
    s = _UNIT_RE.sub("", s)
    for pat, rep in _LATEX_SUBS:
        s = re.sub(pat, rep, s)
    for w, d in _WORD_NUMBERS.items():
        s = re.sub(rf"\b{w}\b", d, s, flags=re.IGNORECASE)
    s = _fix_binom(s)  # before fracs: brace structure must survive
    s = _fix_sqrt(s)  # before fracs: \frac{\sqrt{3}}{3} loses inner braces
    s = _fix_fracs(s)
    # "x = 5" / "k=5" style prefixes: keep the value side.  lhs must be a
    # bare variable name — '<='/'>=' from the \le/\ge rewrites must NOT
    # count, else inequalities collapse to their number
    if s.count("=") == 1:
        lhs, rhs = s.split("=")
        lhs = lhs.strip()
        if len(lhs) <= 2 and lhs.isalnum() and rhs.strip():
            s = rhs
    s = s.replace("^", "**")
    # whitespace first so '(1, 234)' and '(1,234)' normalise identically,
    # THEN thousands separators inside digit groups — ambiguous 3-digit
    # tuples resolve to the same reading on both sides of a comparison
    s = re.sub(r"\s+", "", s)
    s = re.sub(r"(\d),(?=\d{3}(\D|$))", r"\1", s)
    s = s.replace("{", "(").replace("}", ")")
    s = _fix_mixed_number(s)
    # ".5" -> "0.5", "2.0" -> "2"
    s = re.sub(r"(?<![\d.])\.(\d)", r"0.\1", s)
    s = re.sub(r"(\d+)\.0+(?=\D|$)", r"\1", s)
    # drop a single unbalanced paren at either end; never touch balanced
    # ones, and never touch half-open intervals like '[1/2, 1)' where the
    # 'unbalanced' paren is matched by a square bracket
    if "[" not in s and "]" not in s:
        if s.count("(") > s.count(")"):
            if s.endswith("("):
                s = s[:-1]
            elif s.startswith("("):
                s = s[1:]
        elif s.count(")") > s.count("("):
            if s.startswith(")"):
                s = s[1:]
            elif s.endswith(")"):
                s = s[:-1]
    return s.lower()


# --------------------------------------------------------------------------
# comparison
# --------------------------------------------------------------------------


def _to_number(s: str) -> Optional[float]:
    try:
        return float(s)
    except (ValueError, TypeError):
        pass
    m = re.fullmatch(r"\(*\(?(-?[\d\.]+)\)?/\(?(-?[\d\.]+)\)?\)*", s)
    if m:
        try:
            return float(m.group(1)) / float(m.group(2))
        except (ValueError, ZeroDivisionError):
            return None
    return None


def _split_top_level(s: str) -> Optional[List[str]]:
    """'(a,b,c)' / '[a,b)' -> top-level comma split, else None."""
    if len(s) < 2 or s[0] not in "([" or s[-1] not in ")]":
        return None
    inner = s[1:-1]
    parts, depth, cur = [], 0, ""
    for c in inner:
        if c in "([":
            depth += 1
        elif c in ")]":
            depth -= 1
        if c == "," and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += c
    parts.append(cur)
    return parts if len(parts) > 1 else None


def _pmatrix_rows(s: str) -> Optional[List[List[str]]]:
    m = re.fullmatch(r"\\begin\(pmatrix\)(.*)\\end\(pmatrix\)", s)
    if not m:
        return None
    return [row.split("&") for row in m.group(1).split("\\\\") if row]


def _numeric_eval(s: str) -> Optional[float]:
    """Float value of a closed-form expression (sqrt/pi/binomial/fractions),
    None when it stays symbolic (free variables) or fails to parse."""
    import sympy
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    try:
        e = parse_expr(
            s,
            transformations=standard_transformations
            + (implicit_multiplication_application,),
            evaluate=True,
        )
        if e.free_symbols:
            return None
        v = sympy.N(e)
        if v.is_real is False:
            return None
        return float(v)
    except Exception:  # noqa: BLE001 — not numerically evaluable
        return None


def _sympy_equal(p: str, t: str) -> bool:
    import sympy
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    transforms = standard_transformations + (implicit_multiplication_application,)

    def parse(s):
        return parse_expr(s, transformations=transforms, evaluate=True)

    try:
        pe, te = parse(p), parse(t)
    except Exception:  # noqa: BLE001 — unparseable => not equal
        return False
    try:
        if pe == te:
            return True
        diff = sympy.simplify(pe - te)
        return diff == 0
    except Exception:  # noqa: BLE001
        return False


def math_equal(
    pred: str,
    target: str,
    rel_tol: float = 1e-4,
    include_percentage: bool = True,
    depth: int = 0,
) -> bool:
    """Graded equivalence (reference math_parser.math_equal:495): exact
    string -> numeric (with /100, x100 percentage forms) -> element-wise
    tuples/intervals/matrices -> equation sides -> sympy symbolic."""
    if pred is None or target is None:
        return False
    p, t = normalize_answer(str(pred)), normalize_answer(str(target))
    if p == t:
        return True

    pn, tn = _to_number(p), _to_number(t)
    if pn is not None and tn is not None:
        candidates = [tn]
        if include_percentage:
            candidates = [tn / 100.0, tn, tn * 100.0]
        return any(
            abs(pn - c) <= rel_tol * max(1.0, abs(c)) for c in candidates
        )
    if (pn is None) != (tn is None):
        # decimal vs closed form ("1.618..." vs (1+sqrt(5))/2): evaluate the
        # symbolic side numerically and compare under the same tolerance —
        # with the same percentage candidates as the numeric-numeric branch,
        # so equivalent (pred, target) pairs score identically either way
        sym, num = (t, pn) if pn is not None else (p, tn)
        val = _numeric_eval(sym)
        if val is not None:
            candidates = [val]
            if include_percentage:
                candidates = [val / 100.0, val, val * 100.0]
            return any(
                abs(num - c) <= rel_tol * max(1.0, abs(c))
                for c in candidates
            )

    if depth < 3:
        # tuples / intervals / coordinate pairs: element-wise
        pp, tt = _split_top_level(p), _split_top_level(t)
        if pp is not None and tt is not None:
            if len(pp) != len(tt) or p[0] != t[0] or p[-1] != t[-1]:
                return False
            return all(
                math_equal(a, b, rel_tol, include_percentage, depth + 1)
                for a, b in zip(pp, tt)
            )
        # matrices: element-wise over rows
        pm, tm = _pmatrix_rows(p), _pmatrix_rows(t)
        if pm is not None and tm is not None:
            if len(pm) != len(tm):
                return False
            return all(
                len(pr) == len(tr)
                and all(
                    math_equal(a, b, rel_tol, include_percentage, depth + 1)
                    for a, b in zip(pr, tr)
                )
                for pr, tr in zip(pm, tm)
            )
        # single equations: compare both sides
        if p.count("=") == 1 and t.count("=") == 1:
            pl, pr = p.split("=")
            tl, tr = t.split("=")
            return math_equal(
                pl, tl, rel_tol, include_percentage, depth + 1
            ) and math_equal(pr, tr, rel_tol, include_percentage, depth + 1)

    return _sympy_equal(p, t)


# --------------------------------------------------------------------------
# reward functions (signature: prompt, completion, prompt_ids, completion_ids,
# **data -> float; reference: areal/reward usage in workflows)
# --------------------------------------------------------------------------


def gsm8k_reward_fn(prompt, completions, prompt_ids, completion_ids, answer, **kw):
    pred = extract_answer(completions, strict=True)
    return float(pred is not None and math_equal(pred, answer))


def math_verify_reward(prompt, completions, prompt_ids, completion_ids, solution=None,
                       answer=None, **kw):
    target = answer if answer is not None else extract_answer(solution or "",
                                                              strict=False)
    pred = extract_answer(completions, strict=True)
    return float(pred is not None and target is not None and math_equal(pred, target))
