"""Async HTTP with retry (reference: areal/utils/http.py arequest_with_retry).

Retry semantics (ISSUE 11 satellite): failures fall into three classes
and only two of them are always safe to retry.

- *never sent* (connect refused / DNS / connect-phase timeout): the
  handler provably did not run — always retryable.
- *retryable status* (408/425/429/5xx): the server answered and asked
  for / implies a retry, but for 5xx the handler may have partially run,
  so a non-idempotent request must not be replayed blindly.
- *ambiguous* (read timeout, mid-response disconnect): the request may
  have committed server-side; replaying a non-idempotent request here
  double-applies it.

Callers declare ``idempotent=`` honestly: GETs and version polls are,
`/generate` (slot allocation + staleness accounting per call) is not —
the remote client owns its own failover/resubmit loop for those.
Other 4xx raise immediately with ``.status`` set (a 409 staleness
rejection must surface, not burn the retry budget).
"""

import asyncio
import random
from typing import Any, Dict, Optional

import aiohttp

from areal_tpu.utils import logging

logger = logging.getLogger("http")

# Statuses worth retrying besides 5xx: request-timeout, too-early,
# rate-limited.  Everything else in 4xx is the caller's bug or an
# application-level rejection and must surface immediately.
RETRYABLE_STATUSES = frozenset({408, 425, 429})


def is_retryable_status(status: int) -> bool:
    return status in RETRYABLE_STATUSES or status >= 500


def _backoff(retry_delay: float, attempt: int) -> float:
    # Full jitter: uniform over [0, cap) so a killed backend's clients
    # don't re-converge on the survivor in synchronized waves.
    return random.uniform(0, retry_delay * (2**attempt))


def get_default_connector() -> aiohttp.TCPConnector:
    # A fresh connector per session: sessions are created per-request-context
    # on the runner's event loop, and connectors cannot be shared across loops.
    return aiohttp.TCPConnector(limit=0, ttl_dns_cache=300)


class HttpRequestError(RuntimeError):
    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _never_sent(exc: BaseException) -> bool:
    """True when the request provably never reached a handler."""
    return isinstance(
        exc,
        (
            aiohttp.ClientConnectorError,
            aiohttp.ClientProxyConnectionError,
            ConnectionRefusedError,
        ),
    )


async def arequest_with_retry(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    session: Optional[aiohttp.ClientSession] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
    idempotent: bool = True,
) -> Dict[str, Any]:
    """JSON request (default) or raw-bytes upload (`data` + `headers`)
    with retry/backoff.  `timeout` applies per request even on a shared
    session (aiohttp per-request override).  With ``idempotent=False``,
    only never-sent connection failures are retried; ambiguous failures
    and 5xx raise so the caller can decide (e.g. fail over)."""
    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    owns_session = session is None
    if owns_session:
        session = aiohttp.ClientSession(connector=get_default_connector())
    req_timeout = aiohttp.ClientTimeout(
        total=timeout, sock_connect=min(30, timeout)
    )
    try:
        for attempt in range(max_retries):
            try:
                kwargs: Dict[str, Any] = {"timeout": req_timeout}
                if data is not None:
                    kwargs["data"] = data
                    kwargs["headers"] = {
                        "Content-Type": "application/octet-stream",
                        **(headers or {}),
                    }
                elif method != "GET":
                    kwargs["json"] = payload
                async with session.request(method, url, **kwargs) as resp:
                    if resp.status == 200:
                        ctype = resp.headers.get("Content-Type", "")
                        if "application/json" in ctype:
                            return await resp.json()
                        return {"text": await resp.text()}
                    body = await resp.text()
                    last_exc = HttpRequestError(
                        f"{method} {url} -> HTTP {resp.status}: {body[:200]}",
                        status=resp.status,
                    )
                    if not is_retryable_status(resp.status):
                        raise last_exc
                    if not idempotent:
                        # the handler ran (5xx may have side effects):
                        # replaying a non-idempotent request is on the caller
                        raise last_exc
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                last_exc = e
                if not idempotent and not _never_sent(e):
                    # ambiguous: sent but outcome unknown — don't replay
                    raise HttpRequestError(
                        f"{method} {url} failed ambiguously "
                        f"(non-idempotent, not retried): {e!r}"
                    ) from e
            if attempt < max_retries - 1:
                await asyncio.sleep(_backoff(retry_delay, attempt))
        raise HttpRequestError(
            f"request to {url} failed after {max_retries} attempts",
            status=getattr(last_exc, "status", None),
        ) from last_exc
    finally:
        if owns_session:
            await session.close()


async def apost_bytes_with_retry(
    addr: str,
    endpoint: str,
    data: bytes,
    headers: Optional[Dict[str, str]] = None,
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    session: Optional[aiohttp.ClientSession] = None,
    idempotent: bool = True,
) -> Dict[str, Any]:
    """POST a raw `application/octet-stream` body (weight-chunk fast path:
    no base64 inflation, no json parse per chunk)."""
    return await arequest_with_retry(
        addr=addr,
        endpoint=endpoint,
        method="POST",
        max_retries=max_retries,
        timeout=timeout,
        retry_delay=retry_delay,
        session=session,
        data=data,
        headers=headers,
        idempotent=idempotent,
    )


def request_with_retry_sync(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    idempotent: bool = True,
) -> Dict[str, Any]:
    """Blocking variant for non-async contexts (launchers, tools).
    Same three-class retry semantics as `arequest_with_retry`."""
    import time

    import requests

    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            resp = requests.request(
                method,
                url,
                json=payload if method != "GET" else None,
                timeout=timeout,
            )
            if resp.status_code == 200:
                try:
                    return resp.json()
                except ValueError:
                    return {"text": resp.text}
            last_exc = HttpRequestError(
                f"{method} {url} -> HTTP {resp.status_code}: {resp.text[:200]}",
                status=resp.status_code,
            )
            if not is_retryable_status(resp.status_code):
                raise last_exc
            if not idempotent:
                raise last_exc
        except OSError as e:
            last_exc = e
            never_sent = isinstance(
                e, (requests.exceptions.ConnectionError, ConnectionRefusedError)
            ) and not isinstance(e, requests.exceptions.ReadTimeout)
            if not idempotent and not never_sent:
                raise HttpRequestError(
                    f"{method} {url} failed ambiguously "
                    f"(non-idempotent, not retried): {e!r}"
                ) from e
        if attempt < max_retries - 1:
            time.sleep(_backoff(retry_delay, attempt))
    raise HttpRequestError(
        f"request to {url} failed after {max_retries} attempts",
        status=getattr(last_exc, "status", None),
    ) from last_exc
