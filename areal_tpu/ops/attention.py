"""Segment-masked attention with a Pallas splash-attention fast path.

Role counterpart of the reference's flash-attn varlen attention
(realhf/impl/model/modules/attn.py:307: flash_attn_varlen_func over packed
cu_seqlens batches) and of the SDPA fallback in lite's HF models.  TPU-first
design differences:

- Packed variable-length batches are expressed with **segment ids** (-1 =
  padding), not cu_seqlens; causality is by buffer index, which equals
  per-segment position order because packed segments are contiguous.
- The fast path is the TPU splash-attention Pallas kernel
  (`jax.experimental.pallas.ops.tpu.splash_attention`): blockwise online
  softmax, never materialises the [T, S] score matrix, and skips fully-masked
  key blocks — the property that makes 32k-context training feasible where
  the naive einsum path's O(T^2) memory is hopeless (VERDICT.md missing #4).
- GQA runs the MQA kernel vmapped over kv heads (q grouped per kv head).
- Under a `jax.sharding.Mesh` the kernel is wrapped in `shard_map`: batch
  rows over (dp, fsdp), kv heads over tp, and the **query sequence over sp**
  (the kernel is built with q_seq_shards so its block schedule stays
  causal-load-balanced).  K/V stay whole along the sequence — GSPMD inserts
  the all-gather — which is the DeepSpeed-Ulysses memory regime the
  reference gets from areal/utils/ulysses.py.
- The naive einsum path remains for CPU tests, odd head dims, and as the
  numerical reference; both paths share one public entry point.
"""
# areal-lint: hot-path

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

try:  # TPU-only kernels; import lazily guarded so CPU tests work
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as _sk,
    )
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as _sm,
    )

    HAVE_SPLASH = True
except Exception:  # pragma: no cover
    HAVE_SPLASH = False

MASK_VALUE = -2.3819763e38


@jax.custom_jvp
def _pin(x: jax.Array) -> jax.Array:
    """Identity that lowers to `lax.optimization_barrier`, with a pass-through
    tangent: the barrier has no differentiation rule on the installed jaxlib,
    and the bit-identity contract it protects (see `naive_attention`) only
    covers the inference forward — training gradients flow through the
    unbarriered graph unchanged."""
    return jax.lax.optimization_barrier(x)


@_pin.defjvp
def _pin_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    return jax.lax.optimization_barrier(x), t


def _shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` became a top-level API only recently; older jaxlibs
    (0.4.x) ship it as `jax.experimental.shard_map.shard_map` with the
    replication check spelled `check_rep`.  One shim keeps both call sites
    working across the installed range instead of failing with
    AttributeError on the older runtime."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )

# Tests flip this to run the Pallas kernels in interpret mode on the CPU
# mesh — the only way to exercise the sharded splash path without 8 chips.
INTERPRET = False


# ---------------------------------------------------------------------------
# Naive reference path (CPU fallback + numerics oracle)
# ---------------------------------------------------------------------------


def make_attention_mask(
    segment_ids: jax.Array,
    positions: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """[B, T] segment ids (-1 = pad) -> bool [B, 1, T, T] mask.

    Causality is by *position within the segment*, so packed layouts where
    each sequence restarts positions at 0 are handled uniformly with padded
    layouts (positions strictly increase inside a segment).
    """
    seg_q = segment_ids[:, :, None]
    seg_k = segment_ids[:, None, :]
    same = (seg_q == seg_k) & (seg_q >= 0)
    pos_q = positions[:, :, None]
    pos_k = positions[:, None, :]
    causal = pos_k <= pos_q
    mask = same & causal
    if sliding_window is not None:
        mask &= pos_k > pos_q - sliding_window
    return mask[:, None, :, :]


def naive_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k: jax.Array,  # [B, S, Hkv, hd]
    v: jax.Array,  # [B, S, Hkv, hd]
    mask: jax.Array,  # bool [B, 1, T, S]
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Grouped-query attention with fp32 softmax. Returns [B, T, Hq, hd]."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    q = q.reshape(B, T, Hkv, group, hd)
    scores = jnp.einsum("btkgh,bskh->bkgts", q, k).astype(jnp.float32)
    scores *= 1.0 / np.sqrt(hd)
    if logit_softcap:
        # barrier-pinned: XLA's algebraic simplifier merges the scale /
        # softcap constants differently depending on the surrounding
        # graph, which breaks the bit-identity contract between this
        # dense path and the ragged Pallas kernel (ops/ragged_decode.py
        # pins the same literal sequence).  The barriers force the
        # written div/tanh/mul order in every compilation context.
        scores = _pin(scores)
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
        scores = _pin(scores)
    mask = mask[:, :, None, :, :] if mask.ndim == 4 else mask  # [B,1,1,T,S]
    scores = jnp.where(mask, scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs.astype(v.dtype), v)
    return out.reshape(B, T, Hq, hd)


# ---------------------------------------------------------------------------
# Splash kernel construction
# ---------------------------------------------------------------------------


def splash_supported(T: int, Hq: int, Hkv: int, hd: int, sp: int = 1) -> bool:
    """Shapes the kernel handles well; everything else takes the naive path.
    `sp` = sequence shards: each shard's query extent must stay blockable."""
    return (
        HAVE_SPLASH
        and (jax.default_backend() == "tpu" or INTERPRET)
        and T >= 256
        and T % (128 * sp) == 0
        and hd % 128 == 0
        and Hq % Hkv == 0
    )


def _mask_for(T: int, sliding_window: Optional[int]) -> "_sm.Mask":
    if sliding_window is not None:
        # causal left-window: q - w < k <= q
        return _sm.LocalMask((T, T), (sliding_window - 1, 0), 0)
    return _sm.CausalMask((T, T))


@functools.lru_cache(maxsize=64)
def _make_kernel(
    T: int,
    group: int,
    sliding_window: Optional[int],
    logit_softcap: Optional[float],
    q_seq_shards: int,
    interpret: bool = False,
):
    """Build (and cache — mask-info preprocessing is host-side numpy) the
    MQA splash kernel for one (seq-len, q-group) shape."""
    mask = _sm.MultiHeadMask([_mask_for(T, sliding_window) for _ in range(group)])
    # block sizes must DIVIDE the per-shard query extent (the kernel
    # rejects them otherwise): largest 128-multiple <= 512 that divides —
    # e.g. a 768-token packed row gets 384, not a crashing 512.
    # splash_supported guarantees ext % 128 == 0, so the search always
    # terminates at >= 128; assert rather than loop to 0 for direct callers
    ext = T // q_seq_shards
    if ext % 128:
        raise ValueError(
            f"per-shard query extent {ext} must be a multiple of 128 "
            "(gate shapes through splash_supported)"
        )
    block = min(512, ext)
    while ext % block:
        block -= 128
    block_sizes = _sk.BlockSizes(
        block_q=block,
        block_kv=block,
        block_kv_compute=block,
        block_q_dkv=block,
        block_kv_dkv=block,
        block_kv_dkv_compute=block,
        block_q_dq=block,
        block_kv_dq=block,
    )
    # make_* calls jnp.array on the host-side mask info; when the kernel is
    # first built during a jit trace (lru_cache defers to first use) that
    # would capture per-trace tracers in the cached kernel and leak them
    # into later traces — force concrete compile-time values instead
    with jax.ensure_compile_time_eval():
        return _sk.make_splash_mqa_single_device(
            mask=mask,
            block_sizes=block_sizes,
            attn_logits_soft_cap=logit_softcap,
            q_seq_shards=q_seq_shards,
            interpret=interpret,
        )


def _splash_call(kernel, q, k, v, segment_ids, group: int):
    """q [B, T, Hq, hd], k/v [B, T, Hkv, hd], segment_ids [B, T] ->
    [B, T, Hq, hd].  vmap over batch and kv heads of the MQA kernel."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    qs = (q * float(1.0 / np.sqrt(hd))).transpose(0, 2, 1, 3)  # [B, Hq, T, hd]
    qs = qs.reshape(B, Hkv, group, T, hd)
    ks = k.transpose(0, 2, 1, 3)  # [B, Hkv, T, hd]
    vs = v.transpose(0, 2, 1, 3)

    def per_row(qr, kr, vr, seg):
        sids = _sk.SegmentIds(q=seg, kv=seg)
        return jax.vmap(kernel, in_axes=(0, 0, 0, None))(qr, kr, vr, sids)

    out = jax.vmap(per_row)(qs, ks, vs, segment_ids)  # [B, Hkv, group, T, hd]
    return out.reshape(B, Hq, T, hd).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------


def ring_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k: jax.Array,  # [B, T, Hkv, hd]
    v: jax.Array,  # [B, T, Hkv, hd]
    segment_ids: jax.Array,  # int32 [B, T], -1 = padding
    positions: jax.Array,  # int32 [B, T]
    mesh: Mesh,
    sliding_window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Ring attention over the `sp` mesh axis: K/V are SHARDED along the
    sequence (unlike the splash path, where K/V stay whole per shard — the
    Ulysses memory regime) and rotate around the ring via `ppermute`, with
    a blockwise online softmax accumulating each visiting block.

    This is the context-parallel regime the reference lacks outright
    (SURVEY.md §2.4 "Ring attention: not present"): per-chip attention
    memory is O(T/sp) for q AND k/v, so the context ceiling scales with the
    ring size.  Differentiable (shard_map + ppermute transpose), segment-
    masked, GQA-aware; causality and the optional sliding window are
    evaluated per visiting block from the rotating (positions, segment_ids)
    metadata, so packed rows work exactly as in the naive/splash paths.
    """
    sp = mesh.shape["sp"]
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    batch = ("dp", "fsdp", "ep")
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    scale = float(1.0 / np.sqrt(hd))

    def body(qb, kb, vb, segq, posq, segk, posk):
        # qb [b, Tl, Hkv_l, group, hd]; kb/vb [b, Tl, Hkv_l, hd]
        b, Tl = qb.shape[:2]
        hkv = kb.shape[2]
        m = jnp.full((b, hkv, group, Tl), MASK_VALUE, jnp.float32)
        l = jnp.zeros((b, hkv, group, Tl), jnp.float32)
        acc = jnp.zeros((b, hkv, group, Tl, hd), jnp.float32)
        for _ in range(sp):
            scores = jnp.einsum(
                "btkgh,bskh->bkgts", qb, kb
            ).astype(jnp.float32) * scale
            if logit_softcap:
                scores = jnp.tanh(scores / logit_softcap) * logit_softcap
            mask = (
                (segq[:, :, None] == segk[:, None, :])
                & (segq[:, :, None] >= 0)
                & (posk[:, None, :] <= posq[:, :, None])
            )
            if sliding_window is not None:
                mask &= posk[:, None, :] > posq[:, :, None] - sliding_window
            mask = mask[:, None, None, :, :]  # [b,1,1,Tl,Ts]
            # mask BEFORE the exp so its argument is always <= 0: raw masked
            # scores minus m_new == MASK_VALUE would overflow exp to inf in
            # the unselected where-branch and poison the backward (the
            # where-grad trap); the outer where still zeroes the
            # exp(0) == 1 that all-masked rows (m_new == MASK_VALUE) produce
            smx = jnp.where(mask, scores, MASK_VALUE)
            m_new = jnp.maximum(m, jnp.max(smx, axis=-1))
            p = jnp.where(mask, jnp.exp(smx - m_new[..., None]), 0.0)
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1)
            acc = acc * corr[..., None] + jnp.einsum(
                "bkgts,bskh->bkgth", p, vb.astype(jnp.float32)
            )
            m = m_new
            kb, vb, segk, posk = (
                jax.lax.ppermute(x, "sp", perm) for x in (kb, vb, segk, posk)
            )
        out = acc / jnp.maximum(l[..., None], 1e-20)  # pad rows: l == 0 -> 0
        return out.astype(qb.dtype)

    qg = q.reshape(B, T, Hkv, group, hd)
    out = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(batch, "sp", "tp", None, None),  # q [B, T, Hkv, group, hd]
            P(batch, "sp", "tp", None),  # k — sequence SHARDED
            P(batch, "sp", "tp", None),  # v
            P(batch, "sp"),  # q-side segment ids
            P(batch, "sp"),  # q-side positions
            P(batch, "sp"),  # rotating k-side segment ids
            P(batch, "sp"),  # rotating k-side positions
        ),
        out_specs=P(batch, "tp", None, "sp", None),  # [B, Hkv, group, T, hd]
        check_vma=False,
    )(qg, k, v, segment_ids, positions, segment_ids, positions)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, Hq, hd)


def segment_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k: jax.Array,  # [B, T, Hkv, hd]
    v: jax.Array,  # [B, T, Hkv, hd]
    segment_ids: jax.Array,  # int32 [B, T], -1 = padding
    positions: jax.Array,  # int32 [B, T] (per-segment positions)
    sliding_window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
    impl: str = "auto",  # auto | splash | naive | ring
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Causal segment-masked self-attention over packed/padded rows.

    Requires packed segments to be contiguous with per-segment positions
    increasing by 1 per buffer slot (the layout `pack_into_rows` emits), so
    buffer-index causality equals position causality — the invariant that
    lets the splash kernel use its lazy causal mask instead of a
    materialised one.
    """
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    if impl == "ring":
        if mesh is not None and mesh.shape.get("sp", 1) > 1:
            return ring_attention(
                q, k, v, segment_ids, positions, mesh,
                sliding_window=sliding_window, logit_softcap=logit_softcap,
            )
        impl = "auto"  # no ring without an sp axis — use the normal ladder
    if impl == "auto":
        sp = mesh.shape["sp"] if mesh is not None else 1
        impl = "splash" if splash_supported(T, Hq, Hkv, hd, sp=sp) else "naive"
    if impl == "naive":
        mask = make_attention_mask(segment_ids, positions, sliding_window)
        return naive_attention(q, k, v, mask, logit_softcap)

    group = Hq // Hkv
    segment_ids = segment_ids.astype(jnp.int32)
    if mesh is None or all(s == 1 for s in mesh.shape.values()):
        kernel = _make_kernel(
            T, group, sliding_window, logit_softcap, 1, interpret=INTERPRET
        )
        return _splash_call(kernel, q, k, v, segment_ids, group)
    return _sharded_splash(
        q, k, v, segment_ids, mesh, group, sliding_window, logit_softcap
    )


def _sharded_splash(
    q, k, v, segment_ids, mesh: Mesh, group, sliding_window, logit_softcap
):
    """shard_map-wrapped splash: batch over (dp, fsdp), kv heads over tp,
    query sequence over sp; K/V whole along sequence (Ulysses memory
    regime).  The kernel is built with q_seq_shards and its mask-info arrays
    are sharded with `manual_sharding_spec` so each sp shard runs only its
    causally-needed blocks."""
    sp = mesh.shape["sp"]
    T = q.shape[1]
    kernel = _make_kernel(
        T, group, sliding_window, logit_softcap, sp, interpret=INTERPRET
    )
    kernel_spec = kernel.manual_sharding_spec(
        NamedSharding(mesh, P(None, "sp"))  # (head, q_seq) mask-info layout
    )
    batch = ("dp", "fsdp", "ep")

    def body(kern, qs, ks, vs, seg_q, seg_kv):
        def per_row(qr, kr, vr, sq, skv):
            sids = _sk.SegmentIds(q=sq, kv=skv)
            return jax.vmap(kern, in_axes=(0, 0, 0, None))(qr, kr, vr, sids)

        return jax.vmap(per_row)(qs, ks, vs, seg_q, seg_kv)

    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    qs = (q * float(1.0 / np.sqrt(hd))).transpose(0, 2, 1, 3).reshape(B, Hkv, group, T, hd)
    ks = k.transpose(0, 2, 1, 3)
    vs = v.transpose(0, 2, 1, 3)
    out = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            kernel_spec,
            P(batch, "tp", None, "sp", None),  # q: [B, Hkv, group, T, hd]
            P(batch, "tp", None, None),  # k: [B, Hkv, S, hd] — S whole
            P(batch, "tp", None, None),
            P(batch, "sp"),  # q segment ids
            P(batch, None),  # kv segment ids — whole
        ),
        out_specs=P(batch, "tp", None, "sp", None),
        check_vma=False,
    )(kernel, qs, ks, vs, segment_ids, segment_ids)
    return out.reshape(B, Hq, T, hd).transpose(0, 2, 1, 3)
