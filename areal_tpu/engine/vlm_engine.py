"""JaxVLMEngine: vision-language training on the standard train engine.

Capability counterpart of the reference's VLM train path (lite loads
AutoModelForImageTextToText in BaseHFEngine and threads qwen2-VL mrope
position ids through packing, base_hf_engine.py:261-287).  TPU-first shape:

- the text stack, optimizer, sharding, checkpointing, and loss protocol are
  inherited unchanged from JaxTrainEngine; only `_call_model` changes — it
  runs the vision tower and scatters image embeddings before the decoder
  (models/vision.py forward_vlm_lm);
- batches stay PADDED (one sequence per row, original order) instead of
  FFD row-packed: image patches are matched to placeholder tokens by scan
  order, and repacking would permute sequences out from under their
  pixels.  Filler rows/patches pad the shapes up to shard divisibility, so
  everything remains static under jit.

Batch keys beyond the text ones:
  pixel_values     [N, patch_dim]  pre-patchified pixels, images in
                                   sequence order (AutoProcessor layout)
  patch_img_ids    [N]             image index per patch, -1 = padding
  mrope_positions  [B, L, 3]       optional per-token (t, h, w) positions
                                   (models/vision.py mrope_position_ids)
"""

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.config import TrainEngineConfig
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.vision import forward_vlm_lm, init_vision_params
from areal_tpu.utils.data import RowPackedBatch, VISION_PATCH_KEYS

VISION_KEYS = VISION_PATCH_KEYS


class JaxVLMEngine(JaxTrainEngine):
    # the VLM model seam reads modality keys on top of the text ones
    # (base FORWARD_KEYS doc in jax_train.py)
    FORWARD_KEYS = JaxTrainEngine.FORWARD_KEYS + (
        "pixel_values", "patch_img_ids", "mrope_positions", "patch_pos_hw",
    )

    def __init__(
        self,
        config: TrainEngineConfig,
        model_config: Optional[TransformerConfig] = None,
    ):
        if model_config is None or model_config.vision is None:
            raise ValueError("JaxVLMEngine needs a model_config with .vision")
        if model_config.image_token_id is None:
            raise ValueError("model_config.image_token_id is required")
        super().__init__(config, model_config)

    # ------------------------------------------------------------------

    def initialize(self, addr=None, ft_spec=None) -> None:
        super().initialize(addr=addr, ft_spec=ft_spec)
        if "vision" not in self.params:
            # scratch init of the tower when the checkpoint is text-only
            import jax

            from areal_tpu.parallel import shard_pytree

            host = init_vision_params(
                self.model_config.vision,
                jax.random.PRNGKey(7),
                dtype=jnp.dtype(self.config.param_dtype),
            )
            # vision tower is small: replicate it across the mesh
            from jax.sharding import PartitionSpec as P

            specs = jax.tree_util.tree_map(lambda _: P(), host)
            self.params = dict(self.params)
            self.params["vision"] = shard_pytree(self.mesh, host, specs)
            # optimizer state was initialised from the text-only tree in
            # super().initialize(); rebuild so moments cover the tower
            if self._optimizer is not None:
                self._build_optimizer(ft_spec)

    # ------------------------------------------------------------------

    def _row_mult(self) -> int:
        """Rows (and patch groups) must divide over the data-parallel mesh
        axes — the ONE definition both _prepare_rows and _stack_mbs use."""
        return (
            self.mesh.shape["dp"]
            * self.mesh.shape["fsdp"]
            * self.mesh.shape.get("ep", 1)
        )

    def _patch_quantum(self) -> int:
        """Patch-count granularity: merge windows (m2) times the dp axes."""
        return self.model_config.vision.spatial_merge_size ** 2 * self._row_mult()

    def _prepare_rows(
        self, batch: Dict[str, np.ndarray], n_mbs: int
    ) -> Tuple[RowPackedBatch, Dict[str, np.ndarray], int]:
        """Identity row-ification: sequence i -> row i (order preserved so
        patch order matches placeholder order), padded with filler rows and
        filler patches to shard divisibility."""
        mask = batch["attention_mask"].astype(bool)
        B, L = mask.shape
        mult = n_mbs * self._row_mult()
        R = ((B + mult - 1) // mult) * mult

        data: Dict[str, np.ndarray] = {}
        for k, v in batch.items():
            if k in VISION_KEYS or k == "attention_mask":
                continue
            if v.ndim >= 2 and v.shape[:2] == (B, L):
                buf = np.zeros((R, *v.shape[1:]), dtype=v.dtype)
                buf[:B] = v
                data[k] = buf
        seg = np.where(mask, 0, -1).astype(np.int32)
        data["segment_ids"] = np.full((R, L), -1, np.int32)
        data["segment_ids"][:B] = seg
        pos = np.maximum(mask.cumsum(-1) - 1, 0).astype(np.int32)
        data["positions"] = np.zeros((R, L), np.int32)
        data["positions"][:B] = pos
        data["input_ids"] = data["input_ids"].astype(np.int32)
        if "loss_mask" in data:
            data["loss_mask"] = data["loss_mask"] * (data["segment_ids"] >= 0)

        # vision: pad the patch dim to shard divisibility with -1-id patches
        # (their merged embeddings land past every real placeholder index)
        pv = batch["pixel_values"]
        ids = batch["patch_img_ids"]
        quantum = n_mbs * self._patch_quantum()
        N = ((pv.shape[0] + quantum - 1) // quantum) * quantum
        pad_pv = np.zeros((N, pv.shape[1]), pv.dtype)
        pad_pv[: pv.shape[0]] = pv
        pad_ids = np.full((N,), -1, np.int32)
        pad_ids[: ids.shape[0]] = ids
        data["pixel_values"] = pad_pv
        data["patch_img_ids"] = pad_ids
        if "patch_pos_hw" in batch:
            pos = np.asarray(batch["patch_pos_hw"], np.int32)
            pad_pos = np.zeros((N, 2), np.int32)
            pad_pos[: pos.shape[0]] = pos
            data["patch_pos_hw"] = pad_pos
        # per-row patch spans: the mb splitter needs them to carve patch
        # arrays along row-group boundaries
        if "patches_per_row" in batch:
            spans = np.zeros(R, np.int64)
            spans[:B] = np.asarray(batch["patches_per_row"], np.int64)
            if int(spans.sum()) != pv.shape[0]:
                raise ValueError(
                    f"patches_per_row sums to {int(spans.sum())} but "
                    f"pixel_values has {pv.shape[0]} patches"
                )
            data["patches_per_row"] = spans
        elif n_mbs > 1:
            raise ValueError(
                "micro-batching a vision batch needs 'patches_per_row' "
                "(emitted by VisionRLVRWorkflow) to split patch arrays"
            )

        placements = [[(i, L)] for i in range(B)] + [[] for _ in range(R - B)]
        return (
            RowPackedBatch(data={}, placements=placements, row_len=L),
            data,
            L,
        )

    def _stack_mbs(self, data, n_mbs: int):
        """[R, ...] -> [n_mbs, R/n_mbs, ...] for token arrays; patch arrays
        are carved along row-group boundaries via the per-row spans and
        re-padded to a common per-mb patch count (uniform shapes for the
        grad-accumulation scan)."""
        vision = {
            k: data.pop(k)
            for k in (*VISION_KEYS, "patches_per_row")
            if k in data
        }
        out = super()._stack_mbs(data, n_mbs)
        pv, ids = vision["pixel_values"], vision["patch_img_ids"]
        pos = vision.get("patch_pos_hw")
        if n_mbs == 1:
            out["pixel_values"] = pv[None]
            out["patch_img_ids"] = ids[None]
            if pos is not None:
                out["patch_pos_hw"] = pos[None]
            return out
        spans = vision["patches_per_row"]
        R = spans.shape[0]
        rpm = R // n_mbs
        bounds = np.concatenate([[0], np.cumsum(spans)]).astype(np.int64)
        lo = [int(bounds[i * rpm]) for i in range(n_mbs)]
        hi = [int(bounds[(i + 1) * rpm]) for i in range(n_mbs)]
        dp_mult = self._patch_quantum()
        pmax = max(max(h - l for l, h in zip(lo, hi)), dp_mult)
        pmax = ((pmax + dp_mult - 1) // dp_mult) * dp_mult
        pv_mb = np.zeros((n_mbs, pmax, pv.shape[1]), pv.dtype)
        ids_mb = np.full((n_mbs, pmax), -1, np.int32)
        pos_mb = None if pos is None else np.zeros((n_mbs, pmax, 2), np.int32)
        for i, (l, h) in enumerate(zip(lo, hi)):
            pv_mb[i, : h - l] = pv[l:h]
            ids_mb[i, : h - l] = ids[l:h]
            if pos_mb is not None:
                pos_mb[i, : h - l] = pos[l:h]
        out["pixel_values"] = pv_mb
        out["patch_img_ids"] = ids_mb
        if pos_mb is not None:
            out["patch_pos_hw"] = pos_mb
        return out

    def _device_batch(self, data, stacked: bool):
        """Per-key sharding: token arrays use the standard batch spec;
        patch arrays shard the patch dim over the row axes (rank-1
        patch_img_ids cannot take the 2-axis token spec).  The host-side
        span metadata never ships to devices."""
        data = {k: v for k, v in data.items() if k != "patches_per_row"}
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from areal_tpu.parallel import batch_spec, distributed

        token_spec = batch_spec()
        row_axes = token_spec[0]
        specs = {}
        for k in data:
            s = P(row_axes) if k in VISION_KEYS else token_spec
            specs[k] = P(None, *s) if stacked else s
        if jax.process_count() > 1:
            return distributed.make_global_batch(self.mesh, specs, data)
        return {
            k: jax.device_put(v, NamedSharding(self.mesh, specs[k]))
            for k, v in data.items()
        }

    def _call_model(self, params, batch):
        mrope = batch.get("mrope_positions")
        if mrope is not None:
            mrope = jnp.moveaxis(mrope, -1, 0)  # [B, L, 3] -> [3, B, L]
        return forward_vlm_lm(
            params,
            self.model_config,
            batch["input_ids"],
            batch["positions"],
            batch["segment_ids"],
            batch["pixel_values"],
            batch["patch_img_ids"],
            mrope_positions=mrope,
            patch_pos_hw=batch.get("patch_pos_hw"),
            mesh=self.mesh,
        )


class JaxVLMLMEngine(JaxVLMEngine, JaxLMEngine):
    """Supervised finetuning on the VLM engine — the counterpart of the
    reference's VLM SFT path (examples/vlm/clevr_count_70k_sft.py over the
    BaseHFEngine VLM branch).  train_lm/evaluate_lm come from the text LM
    engine unchanged; only the model call and batch preparation differ
    (JaxVLMEngine overrides win in the MRO)."""


class VLMPPOActor:
    """GRPO actor for the VLM engine.

    Thin delegation instead of a PPOActor subclass: advantage/logp
    computation and loss/stat handling come from the standard PPOActor by
    composition.  Where the base actor slices rows freely, vision batches
    must carve patch arrays along per-row spans (`select_rows_vision`), so
    this actor owns the minibatch split (contiguous row groups — order
    preserved, pixels follow their sequences) and the dynamic-sampling
    filter (span-aware row selection with image-id renumbering).
    Reference: areal/engine/ppo/actor.py ppo_update (no VLM restrictions)
    over base_hf_engine.py's VLM batches.
    """

    def __init__(self, config, engine: JaxVLMEngine):
        from areal_tpu.engine.ppo.actor import PPOActor

        self._ppo = PPOActor(config, engine)
        self.config = config
        self.engine = engine

    def compute_logp(self, batch):
        return self._ppo.compute_logp(batch)

    def compute_advantages(self, batch):
        self._ppo.compute_advantages(batch)

    def flush_stats(self):
        self._ppo.flush_stats()

    def ppo_update(self, batch):
        from areal_tpu.utils.data import select_rows_vision

        cfg = self.config
        # same consumption-evidence point as PPOActor.ppo_update: the keyed
        # view below drops `versions`/`trace_keys`
        if hasattr(self.engine, "_consume_telemetry"):
            batch = self.engine._consume_telemetry(batch)
        keys = self._ppo.LOSS_KEYS + VISION_KEYS + (
            "mrope_positions", "patches_per_row",
        )
        view = {k: batch[k] for k in keys if k in batch}
        if cfg.dynamic_sampling:
            keep = self._ppo._dynamic_filter(batch)  # needs "rewards"
            if keep is not None:
                view = select_rows_vision(view, keep)

        n_mbs = max(1, cfg.ppo_n_minibatches)
        B = view["input_ids"].shape[0]
        n_mbs = min(n_mbs, B)
        if n_mbs > 1 and "patches_per_row" not in view:
            raise ValueError(
                "ppo_n_minibatches>1 on a vision batch needs "
                "'patches_per_row' (emitted by VisionRLVRWorkflow)"
            )
        # contiguous row groups (not FFD-shuffled like the text path): patch
        # arrays are carved by span, and scan order must keep matching
        # placeholder order inside each minibatch
        edges = np.linspace(0, B, n_mbs + 1).astype(np.int64)
        all_stats = []
        for i in range(n_mbs):
            rows = np.arange(edges[i], edges[i + 1])
            mb = select_rows_vision(view, rows) if n_mbs > 1 else view
            all_stats.append(self._ppo._train_one_mb(mb))
        return all_stats


class JaxVLMPPOActor(JaxVLMEngine):
    """JaxVLMEngine + VLM GRPO surface (mirrors JaxPPOActor's wiring)."""

    def __init__(self, config, model_config=None):
        super().__init__(config, model_config)
        self.actor = VLMPPOActor(config, self)

    def compute_logp(self, batch):
        return self.actor.compute_logp(batch)

    def compute_advantages(self, batch):
        self.actor.compute_advantages(batch)

    def ppo_update(self, batch):
        return self.actor.ppo_update(batch)

    def warm_shapes(self, shapes):
        raise NotImplementedError(
            "warm_shapes builds text-only synthetic batches; the VLM "
            "forward reads pixel_values/patch_img_ids unconditionally, so "
            "a modality-aware warm batch is needed (not yet implemented). "
            "Leave warm_pack_shapes empty for VLM runs."
        )

    def flush_stats(self):
        self.actor.flush_stats()
