"""Tiered decode: bucketed key-window attention over length-cohort slot
blocks (ISSUE 5).  Decode must pay for the occupied span, not the
`max_seq_len` ceiling — while producing BIT-IDENTICAL token streams to the
untiered/unwindowed path at a fixed seed (counter-keyed sampling makes the
streams partition-invariant).  Covers: greedy + sampled parity across tier
layouts, window-on vs window-off parity, a mid-generation tier migration,
a group fan-out sibling landing in a tier, the compile-signature soak
(steady state stays on the K/tier bucket ladder), device-resident decode
state (no per-chunk re-uploads), admission cohort placement, and the
attended-fraction accounting."""

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest, plan_decode_tiers
from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module")
def setup():
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=4, max_seq_len=256, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4, seed=3)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _greedy_reference(cfg, params, prompt, n_new):
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        L = len(seq)
        ids = np.asarray(seq, np.int32)[None]
        pos = np.arange(L, dtype=np.int32)[None]
        seg = np.zeros((1, L), np.int32)
        logits = np.asarray(forward(params, cfg, ids, pos, seg))[0, -1]
        tok = int(np.argmax(logits))
        out.append(tok)
        seq.append(tok)
    return out


def _run(eng, reqs):
    eng.generate_blocking(reqs)
    return [(tuple(r.output_tokens), r.stop_reason) for r in reqs]


def _signature_budget(name):
    """Reference entry from the checked-in C6 signature budget (ISSUE 9)."""
    import json
    import os

    from areal_tpu.analysis.jit_signatures import BUDGET_PATH

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, BUDGET_PATH)) as f:
        return json.load(f)["reference_configs"][name]


def _mixed_reqs(cfg, rng, temperature):
    return [
        GenRequest(rid=f"r{i}", input_ids=rng.integers(0, 97, n).tolist(),
                   max_new_tokens=m, temperature=temperature, top_p=tp)
        for i, (n, m, tp) in enumerate(
            [(10, 6, 1.0), (24, 30, 0.9), (7, 12, 1.0), (40, 9, 1.0)]
        )
    ]


@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_tiered_matches_untiered(setup, temperature):
    """The same mixed-length workload through 1, 2, and 4 tiers (and an
    explicit uneven layout) yields identical per-request token streams —
    the ISSUE 5 bit-parity contract at fixed seed, greedy AND sampled."""
    cfg, params = setup
    layouts = [
        dict(decode_tiers=1),
        dict(decode_tiers=2),
        dict(decode_tiers=3),
        dict(decode_tier_lens=[64, 256], decode_tier_slots=[3, 1]),
    ]
    outs = []
    for kw in layouts:
        rng = np.random.default_rng(11)
        eng = _engine(cfg, params, **kw)
        outs.append(_run(eng, _mixed_reqs(cfg, rng, temperature)))
    for got in outs[1:]:
        assert got == outs[0]


def test_windowed_matches_full_width(setup):
    """decode_window=True (bucketed K) vs decode_window=False (legacy
    full-M attention): identical token streams — the masked columns beyond
    the window contribute exactly zero."""
    cfg, params = setup
    outs = []
    for window in (True, False):
        rng = np.random.default_rng(12)
        eng = _engine(cfg, params, decode_window=window)
        outs.append(_run(eng, _mixed_reqs(cfg, rng, 1.0)))
    assert outs[0] == outs[1]
    # and the windowed engine really attended less than the ceiling
    eng = _engine(cfg, params)
    _run(eng, [GenRequest(rid="w", input_ids=list(range(1, 9)),
                          max_new_tokens=8, temperature=0.0)])
    assert eng.decode_attended_fraction() < 0.5


def test_greedy_group_fanout_sibling_lands_in_tier(setup):
    """A GRPO group fanned out across a length-cohort tier still emits the
    solo greedy rollout per sibling, with the cluster prefix shared (one
    fresh prefill + one copy), tiering composing with ISSUE 2."""
    cfg, params = setup
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 97, 24).tolist()
    ref = _greedy_reference(cfg, params, prompt, 6)
    eng = _engine(cfg, params, decode_tiers=2)
    reqs = [
        GenRequest(rid=f"G-{i}", input_ids=list(prompt), max_new_tokens=6,
                   temperature=0.0, group_id="G", group_n=4)
        for i in range(4)
    ]
    eng.generate_blocking(reqs)
    for r in reqs:
        assert r.output_tokens == ref, r.rid
    assert eng.stats["prefill_calls"] == 1
    assert eng.stats["copy_calls"] == 1
    assert eng.stats["shared_tokens"] == 3 * (len(prompt) - 1)


def test_mid_generation_tier_migration_parity(setup):
    """A long-budget request forced into the short cohort (its tier full)
    migrates mid-generation once a roomier slot frees — device-side
    cache-row copy — and its token stream still matches the untiered
    engine's bit for bit."""
    cfg, params = setup

    def reqs_for(rng):
        # two short-lived long-budget requests claim the long tier; the
        # third (also long-budget) must take a short-tier slot and later
        # outgrow the 64-token cohort ceiling
        blockers = [
            GenRequest(rid=f"b{i}",
                       input_ids=rng.integers(0, 97, 30).tolist(),
                       max_new_tokens=40, temperature=1.0)
            for i in range(2)
        ]
        mover = GenRequest(rid="mover",
                           input_ids=rng.integers(0, 97, 40).tolist(),
                           max_new_tokens=60, temperature=1.0)
        return blockers + [mover]

    tiered = _engine(cfg, params, decode_tier_lens=[64, 256],
                     decode_tier_slots=[2, 2], decode_chunk=4)
    rng = np.random.default_rng(21)
    t_reqs = reqs_for(rng)
    t_out = _run(tiered, t_reqs)
    assert tiered.stats["tier_migrations"] >= 1, tiered.stats

    untiered = _engine(cfg, params, decode_tiers=1, decode_chunk=4)
    rng = np.random.default_rng(21)
    u_out = _run(untiered, reqs_for(rng))
    assert t_out == u_out


def test_compile_signature_soak_stays_on_ladder(setup):
    """Steady-state mixed-length traffic mints ZERO new decode programs
    once the K/tier bucket ladder is warm — the jit-cache-counting pin for
    the ISSUE 5 shape discipline."""
    cfg, params = setup
    eng = _engine(cfg, params, decode_tiers=2, decode_chunk=4)
    rng = np.random.default_rng(31)

    def wave(tag):
        reqs = [
            GenRequest(rid=f"{tag}{i}",
                       input_ids=rng.integers(0, 97, n).tolist(),
                       max_new_tokens=m, temperature=1.0)
            for i, (n, m) in enumerate(
                [(8, 10), (20, 25), (40, 40), (60, 30)]
            )
        ]
        eng.generate_blocking(reqs)

    # two warm rounds: the second covers re-admission over post-decode
    # cache buffers (their sharding signature differs from the cold
    # device_put the very first prefill saw)
    wave("warm0")
    wave("warm1")
    sizes = {
        "decode": eng._decode_fn._cache_size(),
        "prefill": eng._prefill_fn._cache_size(),
    }
    for w in range(3):
        wave(f"soak{w}")
    assert eng._decode_fn._cache_size() == sizes["decode"]
    assert eng._prefill_fn._cache_size() == sizes["prefill"]

    # ISSUE 9: the checked-in signature budget is the authoritative
    # ceiling for this reference config — observed program counts must
    # stay within it, and the config must match what the budget assumed
    # (regenerate with `python scripts/lint.py --write-budget`).
    ref = _signature_budget("tiered_decode_soak")
    assert ref["config"] == {"n_slots": 4, "max_seq_len": 256,
                             "prompt_bucket": 16, "decode_tiers": 2}
    assert eng._decode_fn._cache_size() <= ref["budgets"]["decode"]
    assert eng._prefill_fn._cache_size() <= ref["budgets"]["prefill"]


def test_device_resident_state_between_chunks(setup):
    """Steady-state decode chains device arrays chunk to chunk: the host
    re-uploads state only when admission/free/migration dirties it, never
    per dispatch (the C2 host-upload discipline, runtime-verified)."""
    cfg, params = setup
    eng = _engine(cfg, params, n_slots=2, decode_chunk=4)
    req = GenRequest(rid="long", input_ids=list(range(1, 9)),
                     max_new_tokens=64, temperature=1.0)
    eng.generate_blocking([req])
    assert eng.stats["decode_calls"] >= 10
    # one sync after admission; the free at the end dirties but is never
    # re-uploaded (no further decode) — steady chunks upload nothing
    assert eng.stats["state_syncs"] <= 2, eng.stats


def test_admission_places_by_length_cohort(setup):
    """Budget-based placement: short-budget requests land in the short
    cohort, long-budget in the long one (occupancy observed mid-flight)."""
    cfg, params = setup
    eng = _engine(cfg, params, decode_tier_lens=[64, 256],
                  decode_tier_slots=[2, 2])
    short = [
        GenRequest(rid=f"s{i}", input_ids=list(range(1, 11)),
                   max_new_tokens=8, temperature=1.0)
        for i in range(2)
    ]
    long_ = [
        GenRequest(rid=f"l{i}", input_ids=list(range(1, 41)),
                   max_new_tokens=120, temperature=1.0)
        for i in range(2)
    ]
    for r in short + long_:
        eng.submit(r)
    eng._admit()  # placement observed before decode can finish anything
    assert eng.tier_occupancy() == [2, 2]
    # short cohort slots are exactly the first block
    assert all(
        eng.slot_req[s] is not None and eng.slot_req[s].rid.startswith("s")
        for s in range(2)
    )
    eng.generate_blocking(short + long_)


def test_plan_decode_tiers_layouts():
    lens, slots = plan_decode_tiers(64, 16384, 3, 128)
    assert lens == [4096, 8192, 16384]
    assert slots == [32, 16, 16]
    assert sum(slots) == 64
    lens, slots = plan_decode_tiers(8, 2048, 1, 128)
    assert (lens, slots) == ([2048], [8])
    with pytest.raises(ValueError):
        plan_decode_tiers(2, 2048, 4, 128)


def test_tier_layout_validation(setup):
    cfg, params = setup
    with pytest.raises(ValueError):
        _engine(cfg, params, decode_tier_lens=[64, 256],
                decode_tier_slots=[2, 3])  # sums to 5 != 4
    with pytest.raises(ValueError):
        _engine(cfg, params, decode_tier_lens=[256, 64],
                decode_tier_slots=[2, 2])  # ceilings must ascend
    with pytest.raises(ValueError):
        _engine(cfg, params, decode_tier_lens=[64, 256])  # lens without slots
