"""C4 — dead modules: package code no runtime entry point can reach.

VERDICT r5 flagged `dataset/gsm8k_synth.py` shipped with zero importers;
this checker finds that class mechanically.  Semantics: a module under the
package is ALIVE iff it is reachable through the import graph from a
non-test root:

- roots are every scanned file OUTSIDE the package tree (scripts/,
  examples/, bench.py, other top-level modules) plus any package module
  with an ``if __name__ == "__main__":`` guard (an executable entry
  point, e.g. `python -m areal_tpu.gen.server`);
- edges are `import` / `from ... import ...` statements (relative imports
  resolved), `importlib.import_module("...")` / `__import__("...")` with
  literal arguments, and dotted `areal_tpu.*` strings in alive files
  (launchers spawn `python -m areal_tpu...` command lines);
- importing a submodule executes its parent packages, so parents of alive
  modules are alive; a package `__init__` keeps its submodules alive only
  via its own (re-export) imports.

Reachability — not direct-importer counting — is deliberate: a package
whose `__init__` imports its own submodules but which nothing outside
imports is dead as a whole, and must not keep itself alive through the
internal cycle.  Test-only importers (anything under tests/) never count.

Suppression is file-scoped: a ``# areal-lint: disable=dead-module
<reason>`` anywhere in the module marks it an intentional library/
experimental surface.
"""

import ast
import os
import re
from typing import Dict, List, Set

from areal_tpu.analysis.core import Finding, SourceFile

RULE = "dead-module"

_DOTTED_STR_RE_TMPL = r"{pkg}(?:\.[A-Za-z_]\w*)+"


def _module_name(rel: str) -> str:
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _has_main_guard(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.If):
            test = node.test
            if isinstance(test, ast.Compare):
                names = [
                    n.id for n in ast.walk(test) if isinstance(n, ast.Name)
                ]
                consts = [
                    c.value
                    for c in ast.walk(test)
                    if isinstance(c, ast.Constant)
                ]
                if "__name__" in names and "__main__" in consts:
                    return True
    return False


def _imports_of(sf: SourceFile, rel: str, pkg: str) -> Set[str]:
    """Dotted module names referenced by this file (absolute, with
    relative imports resolved against the file's package path)."""
    out: Set[str] = set()
    if sf.tree is None:
        return out
    # containing package = the file's directory, for modules and for
    # __init__ alike (relative level L resolves against it minus L-1)
    file_pkg = rel[:-3].split(os.sep)[:-1]
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add(a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = file_pkg[: len(file_pkg) - (node.level - 1)]
                mod = ".".join(base + ([node.module] if node.module else []))
            else:
                mod = node.module or ""
            if mod:
                out.add(mod)
                for a in node.names:
                    out.add(f"{mod}.{a.name}")
        elif isinstance(node, ast.Call):
            fname = ""
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in ("import_module", "__import__") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    out.add(arg.value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in re.findall(
                _DOTTED_STR_RE_TMPL.format(pkg=re.escape(pkg)), node.value
            ):
                out.add(m)
    return out


def check_dead_modules(
    root: str, files: Dict[str, SourceFile], package: str = "areal_tpu"
) -> List[Finding]:
    pkg_prefix = package + os.sep
    modules: Dict[str, str] = {}  # dotted -> rel path
    for rel in files:
        if rel.startswith(pkg_prefix):
            modules[_module_name(rel)] = rel

    imports: Dict[str, Set[str]] = {
        rel: _imports_of(sf, rel, package) for rel, sf in files.items()
    }

    # seed: non-package files and executable package modules
    alive: Set[str] = set()
    queue: List[str] = []

    def mark(dotted: str):
        # a reference to pkg.a.b executes pkg and pkg.a on the way in
        parts = dotted.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            if prefix in modules and prefix not in alive:
                alive.add(prefix)
                queue.append(prefix)

    for rel, sf in files.items():
        if rel.startswith(pkg_prefix):
            if sf.tree is not None and _has_main_guard(sf.tree):
                mark(_module_name(rel))
        else:
            for name in imports[rel]:
                mark(name)

    while queue:
        dotted = queue.pop()
        rel = modules[dotted]
        for name in imports.get(rel, ()):
            mark(name)

    findings: List[Finding] = []
    for dotted, rel in sorted(modules.items()):
        if dotted in alive or dotted == package:
            continue
        sf = files[rel]
        f = Finding(
            RULE,
            rel,
            1,
            f"module `{dotted}` is unreachable from any non-test entry "
            "point (scripts/, examples/, top-level modules, or a "
            "__main__ guard) — dead code: wire it in, delete it, or "
            "suppress with a reason",
        )
        sup = sf.file_suppression_for(RULE)
        if sup is not None:
            sup.used = True
            f.suppressed = True
            f.suppress_reason = sup.reason or "(no reason)"
        findings.append(f)
    return findings


def scan_tree(root: str, package: str) -> List[Finding]:
    """Standalone entry for fixture trees: load + check in one call."""
    from areal_tpu.analysis.core import load_files

    return check_dead_modules(root, load_files(root), package=package)
