"""Countdown + search-agent entry points (VERDICT r2 #6): dataset loaders,
the SearchQAAgent tool loop, and launcher end-to-end smoke runs through the
`workflow=countdown|search` branches (reference: examples/countdown/train.py,
examples/search-agent/local_1.5b_example.yaml)."""

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from tests.fixtures import make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_countdown_synthetic_dataset_is_solvable():
    from areal_tpu.agent.countdown_env import verify_countdown
    from areal_tpu.dataset import get_custom_dataset

    rows = get_custom_dataset(path="synthetic:16", type="countdown")
    assert len(rows) == 16
    for r in rows:
        assert {"messages", "numbers", "target", "query_id"} <= set(r)
        # puzzles are built from their own numbers: the generating
        # left-fold expression must verify
        assert str(r["target"]) in r["messages"][0]["content"]


def test_countdown_manifest_loader(tmp_path):
    import json

    from areal_tpu.dataset import get_custom_dataset

    p = tmp_path / "train.jsonl"
    p.write_text(
        json.dumps({"numbers": [3, 7, 2], "target": 21}) + "\n"
    )
    rows = get_custom_dataset(path=str(tmp_path), type="countdown")
    assert rows[0]["numbers"] == [3, 7, 2] and rows[0]["target"] == 21


def test_searchqa_loader_shared_corpus(tmp_path):
    import json

    from areal_tpu.dataset import get_custom_dataset

    (tmp_path / "corpus.txt").write_text(
        "Paris is the capital of France.\nEverest is the highest mountain.\n"
    )
    (tmp_path / "train.jsonl").write_text(
        json.dumps({"question": "Capital of France?", "answer": "Paris"}) + "\n"
    )
    rows = get_custom_dataset(path=str(tmp_path), type="searchqa")
    assert rows[0]["answer"] == "Paris"
    assert len(rows[0]["corpus"]) == 2
    assert "<search>" in rows[0]["messages"][0]["content"]


class _Tok:
    def encode(self, t, add_special_tokens=False):
        return [ord(c) % 256 for c in t]

    def decode(self, t):
        return "".join(chr(x) for x in t)


class _ScriptedEngine:
    """First call emits a <search> query (plus overshoot the agent must
    discard); after the injected <information> block, emits the answer."""

    def __init__(self):
        self.calls = []

    async def agenerate(self, req):
        self.calls.append(list(req.input_ids))
        text = "".join(chr(x) for x in req.input_ids)
        if "<information>" in text:
            out_text = "So the answer is \\boxed{Paris}"
        else:
            out_text = "Let me look. <search>capital France</search> hmm..."
        out = [ord(c) % 256 for c in out_text]

        class R:
            input_tokens = list(req.input_ids)
            output_tokens = out
            output_logprobs = [-0.25] * len(out)
            output_versions = [3] * len(out)
            input_len = len(req.input_ids)
            output_len = len(out)
            stop_reason = "stop"

        return R()


def test_search_agent_tool_loop_injects_information():
    from areal_tpu.agent import AgentWorkflow, SearchQAAgent
    from areal_tpu.agent.search_env import LocalSearchEnv
    from areal_tpu.api.config import GenerationHyperparameters

    corpus = [
        "Paris is the capital of France.",
        "Everest is the highest mountain.",
    ]
    wf = AgentWorkflow(
        SearchQAAgent(
            GenerationHyperparameters(n_samples=1, max_new_tokens=256),
            tokenizer=_Tok(),
        ),
        env_factory=lambda data: LocalSearchEnv(data["corpus"], data["answer"]),
    )
    eng = _ScriptedEngine()
    batch = asyncio.run(
        wf.arun_episode(
            eng,
            {
                "input_ids": _Tok().encode("Q: capital of France?"),
                "corpus": corpus,
                "answer": "Paris",
            },
        )
    )
    assert (batch["rewards"] == 1.0).all()
    # second generation call saw the injected information block
    assert len(eng.calls) == 2
    second_prompt = "".join(chr(x) for x in eng.calls[1])
    assert "<information>" in second_prompt and "Paris is the capital" in second_prompt
    # overshoot past </search> was discarded, injected tokens carry no loss
    ids = batch["input_ids"][0]
    text = "".join(chr(x) for x in ids.tolist())
    assert "hmm" not in text
    lm = np.asarray(batch["loss_mask"][0], bool)
    info_span = text.find("<information>"), text.find("</information>")
    assert not lm[info_span[0]: info_span[1]].any()


def _launch(example_rel, cfg_text, tmp_path, fileroot):
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(cfg_text)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.launcher.local",
         os.path.join(REPO, example_rel), "--config", str(cfg_path)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"launcher timed out.\n{out[-4000:]}")
    trainer_log = ""
    logs = fileroot
    if logs.exists():
        for root, _, files in os.walk(logs):
            for f in files:
                if f.startswith("trainer"):
                    trainer_log += open(os.path.join(root, f)).read()
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\n{out[-2000:]}\n{trainer_log[-4000:]}"
    )
    assert "Step 1/" in trainer_log and "done." in trainer_log, trainer_log[-4000:]


_COMMON = """
seed: 1
total_train_epochs: 1
total_train_steps: 1
async_training: true
cluster:
  fileroot: {fileroot}
allocation_mode: "jax:d1+jax:d1"
gconfig:
  n_samples: 2
  max_new_tokens: 16
  temperature: 1.0
rollout:
  max_concurrent_rollouts: 8
  consumer_batch_size: 4
  max_head_offpolicyness: 2
  request_timeout: 120
gen_server:
  model_path: {ckpt}
  max_seqs: 4
  max_context_len: 256
actor:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  group_size: 2
  ppo_n_minibatches: 1
  pack_length_quantum: 64
  max_pack_length: 256
  adv_norm:
    mean_level: group
    std_level: group
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
saver:
  freq_steps: null
checkpointer:
  freq_steps: null
evaluator:
  freq_steps: null
recover:
  mode: disabled
stats_logger:
  fileroot: {fileroot}
"""


@pytest.mark.slow
def test_countdown_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    fileroot = tmp_path / "exp"
    cfg = (
        "experiment_name: cdsmoke\ntrial_name: t0\nworkflow: countdown\n"
        f"tokenizer_path: {ckpt}\n"
        "train_dataset:\n  path: synthetic:8\n  type: countdown\n"
        "  batch_size: 4\n"
        + _COMMON.format(fileroot=fileroot, ckpt=ckpt)
    )
    _launch("examples/countdown/countdown_grpo.py", cfg, tmp_path, fileroot)


@pytest.mark.slow
def test_search_example_end_to_end(tmp_path):
    import json

    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data_dir = tmp_path / "qa"
    data_dir.mkdir()
    (data_dir / "corpus.txt").write_text(
        "Paris is the capital of France.\nEverest is the highest mountain.\n"
    )
    with open(data_dir / "train.jsonl", "w") as f:
        for i in range(8):
            f.write(json.dumps(
                {"question": f"Capital of France? (v{i})", "answer": "Paris"}
            ) + "\n")
    fileroot = tmp_path / "exp"
    cfg = (
        "experiment_name: sasmoke\ntrial_name: t0\nworkflow: search\n"
        f"tokenizer_path: {ckpt}\n"
        f"train_dataset:\n  path: {data_dir}\n  type: searchqa\n"
        "  batch_size: 4\n"
        + _COMMON.format(fileroot=fileroot, ckpt=ckpt)
    )
    _launch("examples/search_agent/search_grpo.py", cfg, tmp_path, fileroot)
