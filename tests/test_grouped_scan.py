"""Two-level layer-grouped scan (ISSUE 20).

The backbone's layer loop groups `layer_group_size` layers behind ONE
`jax.checkpoint` boundary per outer-scan step.  Grouping is a pure
scheduling change: loss AND grads must stay bitwise identical to the
classic per-layer scan (G=1) on CPU, for every remat rung, with LoRA,
with MoE layers, and under `scan_split_transpose`.  The backward-pass win
is pinned structurally: the total elements written by HLO
dynamic-update-slice ops (the scan-transpose carry traffic the ROADMAP 3b
plateau was bound on) must shrink when G grows.
"""

import re
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config
from areal_tpu.models.transformer import (
    effective_scan_unroll,
    forward_lm,
)

RUNGS = ("full", "dots", "save_attn", "save_mlp", "carry_offload")


def _base_cfg(**kw):
    kw.setdefault("num_layers", 4)
    return tiny_config(vocab_size=64, qkv_bias=True, dtype="float32",
                       param_dtype="float32", **kw)


def _inputs(cfg, seed=0, B=2, L=16):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.zeros((B, L), np.int32)
    return ids, pos, seg


def _loss_and_grad(cfg, params, ids, pos, seg):
    def f(p):
        logits = forward(p, cfg, ids, pos, seg)
        return jax.nn.logsumexp(logits).sum() / ids.size

    try:
        return jax.value_and_grad(f)(params)
    except Exception as e:  # noqa: BLE001 — backend capability probe
        if "annotate_device_placement" in str(e):
            pytest.skip("host-offload custom call not implemented on this "
                        "backend (carry_offload is TPU-targeted)")
        raise


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


@pytest.mark.parametrize("policy", RUNGS)
def test_grouped_scan_bitwise_parity(policy):
    """Every (G, rung) pair reproduces the G=1 loss and grads BITWISE:
    grouping only moves the checkpoint boundary, never the math."""
    base = _base_cfg(remat=True, remat_policy=policy)
    params = init_params(base, jax.random.PRNGKey(0))
    ids, pos, seg = _inputs(base)
    l_ref, g_ref = _loss_and_grad(base.replace(layer_group_size=1),
                                  params, ids, pos, seg)
    for G in (2, 4):
        l_g, g_g = _loss_and_grad(base.replace(layer_group_size=G),
                                  params, ids, pos, seg)
        assert float(l_ref) == float(l_g), (policy, G)
        _assert_trees_equal(g_ref, g_g)


def test_grouped_scan_parity_without_remat():
    """G>1 with remat OFF still matches: the grouped reshape/unrolled chain
    alone is numerics-neutral."""
    base = _base_cfg(remat=False)
    params = init_params(base, jax.random.PRNGKey(1))
    ids, pos, seg = _inputs(base, seed=1)
    l_ref, g_ref = _loss_and_grad(base, params, ids, pos, seg)
    l_g, g_g = _loss_and_grad(base.replace(layer_group_size=2),
                              params, ids, pos, seg)
    assert float(l_ref) == float(l_g)
    _assert_trees_equal(g_ref, g_g)


def test_grouped_scan_split_transpose_parity():
    base = _base_cfg(remat=True, remat_policy="full",
                     scan_split_transpose=True)
    params = init_params(base, jax.random.PRNGKey(2))
    ids, pos, seg = _inputs(base, seed=2)
    l_ref, g_ref = _loss_and_grad(base.replace(layer_group_size=1),
                                  params, ids, pos, seg)
    l_g, g_g = _loss_and_grad(base.replace(layer_group_size=2),
                              params, ids, pos, seg)
    assert float(l_ref) == float(l_g)
    _assert_trees_equal(g_ref, g_g)


def test_grouped_scan_lora_parity():
    """LoRA adds per-layer adapter leaves to params["layers"] — the grouped
    reshape must carry them along with the base weights."""
    from areal_tpu.models.lora import add_lora_params

    base = _base_cfg(remat=True, remat_policy="save_attn", lora_rank=4,
                     lora_alpha=8.0,
                     lora_targets=("q_proj", "v_proj", "o_proj", "up_proj"))
    params = init_params(base.replace(lora_rank=0, lora_targets=()),
                         jax.random.PRNGKey(3))
    params = add_lora_params(params, base, jax.random.PRNGKey(4))
    ids, pos, seg = _inputs(base, seed=3)
    l_ref, g_ref = _loss_and_grad(base.replace(layer_group_size=1),
                                  params, ids, pos, seg)
    l_g, g_g = _loss_and_grad(base.replace(layer_group_size=4),
                              params, ids, pos, seg)
    assert float(l_ref) == float(l_g)
    _assert_trees_equal(g_ref, g_g)


def test_grouped_scan_moe_parity():
    """MoE layers thread the load-balance aux through the scan carry; the
    grouped inner chain must accumulate it identically."""
    cfg = tiny_config(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=2, num_kv_heads=2, num_experts=4, num_experts_per_tok=2,
        moe_capacity_factor=4.0, dtype="float32", param_dtype="float32",
        remat=True, remat_policy="full",
    )
    params = init_params(cfg, jax.random.PRNGKey(5))
    ids, pos, seg = _inputs(cfg, seed=5)

    def run(g):
        c = cfg.replace(layer_group_size=g)

        def f(p):
            out = forward_lm(p, c, ids, pos, seg)
            return (jnp.mean(out.hidden.astype(jnp.float32) ** 2)
                    + out.aux_loss)

        return jax.value_and_grad(f)(params)

    l_ref, g_ref = run(1)
    l_g, g_g = run(2)
    assert float(l_ref) == float(l_g)
    assert float(l_ref) != 0.0  # aux actually flowed
    _assert_trees_equal(g_ref, g_g)


def test_layer_group_size_must_divide_depth():
    cfg = _base_cfg(layer_group_size=3)  # 3 does not divide 4
    params = init_params(cfg.replace(layer_group_size=1),
                         jax.random.PRNGKey(6))
    ids, pos, seg = _inputs(cfg)
    with pytest.raises(ValueError, match="layer_group_size"):
        forward(params, cfg, ids, pos, seg)


def test_scan_unroll_fallback_is_loud():
    """A scan_unroll that does not divide the OUTER scan length warns
    loudly and falls back to 1 (the silent transformer.py:341 fallback
    this satellite removes)."""
    cfg = _base_cfg(num_layers=8, layer_group_size=2, scan_unroll=3)
    with pytest.warns(UserWarning, match="scan_unroll=3"):
        assert effective_scan_unroll(cfg) == 1
    # divisor of the outer length (8/2 = 4): no warning, honoured as-is
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert effective_scan_unroll(cfg.replace(scan_unroll=4)) == 4
        assert effective_scan_unroll(cfg.replace(scan_unroll=1)) == 1


def test_grouping_changes_outer_divisor_contract():
    """unroll=4 divides 8 layers at G=1 but not the 2-group outer scan at
    G=4 — the fallback applies to the OUTER length, bitwise parity holds
    either way."""
    base = _base_cfg(num_layers=8, scan_unroll=4, remat=True,
                     remat_policy="full")
    params = init_params(base, jax.random.PRNGKey(7))
    ids, pos, seg = _inputs(base, seed=7)
    assert effective_scan_unroll(base) == 4
    grouped = base.replace(layer_group_size=4)  # outer length 2: 2 % 4 != 0
    with pytest.warns(UserWarning, match="falling back"):
        assert effective_scan_unroll(grouped) == 1
    out_ref = np.asarray(forward(params, base, ids, pos, seg))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out_g = np.asarray(forward(params, grouped, ids, pos, seg))
    np.testing.assert_array_equal(out_ref, out_g)


# ------------------------- backward-carry HLO proof ---------------------

_DUS_RE = re.compile(r"= \w*\[([\d,]*)\]\S* dynamic-update-slice\(")


def _dus_elements(cfg, params, ids, pos, seg):
    """Total elements written by dynamic-update-slice ops in the OPTIMIZED
    backward HLO.  The raw op COUNT is not monotone in G (XLA fuses and
    re-splits carry updates), but the elements written — the actual carry
    traffic — must shrink as the outer scan gets shorter."""

    def f(p):
        logits = forward(p, cfg, ids, pos, seg)
        return jax.nn.logsumexp(logits).sum()

    txt = jax.jit(jax.grad(f)).lower(params).compile().as_text()
    total = 0
    for m in _DUS_RE.finditer(txt):
        dims = [int(d) for d in m.group(1).split(",") if d]
        n = 1
        for d in dims:
            n *= d
        total += n
    return total


def test_backward_dus_carry_shrinks_with_grouping():
    base = _base_cfg(num_layers=8, remat=True, remat_policy="full")
    params = init_params(base, jax.random.PRNGKey(8))
    ids, pos, seg = _inputs(base, seed=8)
    elems = {
        G: _dus_elements(base.replace(layer_group_size=G),
                         params, ids, pos, seg)
        for G in (1, 2, 4)
    }
    assert elems[2] < elems[1], elems
    assert elems[4] < elems[2], elems


# ------------------------------ engine level ----------------------------


def _engine(layer_group_size=1, remat_policy="full", n_mbs=1,
            num_layers=4, lm_head_chunk=0):
    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.jax_train import JaxTrainEngine

    cfg = TrainEngineConfig(
        experiment_name="t", trial_name="t", init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=True,
        remat_policy=remat_policy,
        layer_group_size=layer_group_size,
        lm_head_chunk=lm_head_chunk,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=n_mbs),
        optimizer=OptimizerConfig(lr=1e-2, warmup_steps_proportion=0.0,
                                  weight_decay=0.0),
        pack_length_quantum=16,
    )
    eng = JaxTrainEngine(cfg, model_config=tiny_config(
        vocab_size=128, qkv_bias=True, num_layers=num_layers,
        hf_architecture="Qwen2ForCausalLM"))
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return eng


def _batch(rng, vocab=128, B=8, L=12):
    lens = rng.integers(4, L + 1, B)
    mask = np.arange(L)[None, :] < lens[:, None]
    ids = rng.integers(0, vocab, (B, L)) * mask
    loss_mask = mask.copy()
    loss_mask[np.arange(B), lens - 1] = False
    return {
        "input_ids": ids.astype(np.int32),
        "attention_mask": mask,
        "loss_mask": loss_mask.astype(np.float32),
    }


def _weight(batch):
    return float(np.sum(batch["loss_mask"]))


def test_engine_grouped_training_is_bitwise_identical():
    """Full engine A/B: identical seeds, G=1 vs G=4, several optimizer
    steps — the loss trajectories must match exactly (the CI train-scan
    A/B gate in .github/workflows/test.yml asserts the same thing through
    scripts/bench_e2e_grpo.py)."""
    from areal_tpu.ops import sft_loss_fn

    def run(G):
        eng = _engine(layer_group_size=G)
        rng = np.random.default_rng(11)
        losses = []
        for _ in range(4):
            batch = _batch(rng)
            losses.append(eng.train_batch(batch, sft_loss_fn, _weight)["loss"])
        return losses

    a, b = run(1), run(4)
    assert a == b, (a, b)
    assert a[-1] < a[0]  # it actually trained


def test_engine_rejects_non_divisor_group_size():
    with pytest.raises(ValueError, match="layer_group_size"):
        _engine(layer_group_size=3)  # 4 layers


def test_engine_stats_record_scan_shape():
    """Train stats carry the compiled scan shape — the loud-fallback
    satellite's artifact half: logs can always tell which scan ran."""
    from areal_tpu.ops import sft_loss_fn

    eng = _engine(layer_group_size=2)
    rng = np.random.default_rng(12)
    stats = eng.train_batch(_batch(rng), sft_loss_fn, _weight)
    assert stats["layer_group_size"] == 2.0
    assert stats["effective_scan_unroll"] == 1.0


def test_engine_precompile_then_train_donation_safety():
    """precompile_train_batch AOT-compiles WITHOUT donating; interleaving
    it with real (donating) steps must neither invalidate live buffers nor
    mint extra signatures."""
    from areal_tpu.ops import sft_loss_fn

    eng = _engine(layer_group_size=4)
    rng = np.random.default_rng(13)
    batch = _batch(rng)
    eng.precompile_train_batch(batch, sft_loss_fn)
    assert len(eng._train_step_cache) == 1
    s1 = eng.train_batch(batch, sft_loss_fn, _weight)
    # re-precompile AFTER a donating step: params were donated by the real
    # step, so this touches the post-step buffers
    eng.precompile_train_batch(batch, sft_loss_fn)
    s2 = eng.train_batch(batch, sft_loss_fn, _weight)
    assert np.isfinite(s1["loss"]) and np.isfinite(s2["loss"])
    assert s2["loss"] < s1["loss"]
    assert len(eng._train_step_cache) == 1


def test_engine_signature_budget_soak():
    """C6 soak: distinct row-length signatures mint exactly one train-step
    program each; repeats (and grouping/remat — engine-lifetime config)
    mint nothing.  Budget pinned in analysis/signature_budget.json."""
    import json
    import os

    from areal_tpu.analysis.jit_signatures import BUDGET_PATH
    from areal_tpu.ops import sft_loss_fn

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, BUDGET_PATH)) as f:
        ref = json.load(f)["reference_configs"]["train_scan_soak"]
    assert ref["config"] == {"train_shapes": 3}

    eng = _engine(layer_group_size=2, remat_policy="save_attn")
    rng = np.random.default_rng(14)

    def full_batch(L, B=8):
        # fixed-length rows: each L maps to exactly one (row_len, rows)
        # signature — random lengths would vary the packed row count and
        # measure the packer, not the scan
        ids = rng.integers(0, 128, (B, L)).astype(np.int32)
        mask = np.ones((B, L), bool)
        loss_mask = mask.astype(np.float32)
        loss_mask[:, -1] = 0.0
        return {"input_ids": ids, "attention_mask": mask,
                "loss_mask": loss_mask}

    for _ in range(2):  # second sweep must be all cache hits
        for L in (16, 32, 64):  # 3 distinct row-length signatures
            eng.train_batch(full_batch(L), sft_loss_fn, _weight)
    assert len(eng._train_step_cache) <= ref["budgets"]["train_step"]


def test_engine_lm_head_chunk_parity():
    """The plumbed vocab_chunk knob changes scheduling only: training with
    a non-default chunk width reproduces the default's loss trajectory to
    float tolerance.  (Padded-tail exactness at non-dividing widths is
    pinned in test_fused_xent.py; this covers the loss-fn plumbing.)"""
    import functools

    from areal_tpu.ops import sft_loss_fn

    def run(chunk):
        loss_fn = (sft_loss_fn if chunk is None
                   else functools.partial(sft_loss_fn, vocab_chunk=chunk))
        eng = _engine()
        rng = np.random.default_rng(15)
        return [
            eng.train_batch(_batch(rng), loss_fn, _weight)["loss"]
            for _ in range(3)
        ]

    a = run(None)  # env default
    b = run(100)  # rounds up to one 128-wide chunk (vocab 128)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_actor_plumbs_lm_head_chunk():
    """PPOActorConfig.lm_head_chunk reaches the jitted GRPO loss partial
    and the logp-recompute hook (actor.py _build_loss_fn/_get_logp_hook)."""
    from areal_tpu.api.config import PPOActorConfig
    from areal_tpu.engine.ppo.actor import PPOActor

    cfg = PPOActorConfig(
        experiment_name="t", trial_name="t", init_from_scratch=True,
        lm_head_chunk=4096,
    )
    actor = PPOActor(cfg, engine=None)
    loss_fn = actor._build_loss_fn()
    assert loss_fn.keywords["vocab_chunk"] == 4096
    # 0 must fall back to the env default (None), not a 0-wide chunk
    import dataclasses

    actor0 = PPOActor(dataclasses.replace(cfg, lm_head_chunk=0), engine=None)
    assert actor0._build_loss_fn().keywords["vocab_chunk"] is None
