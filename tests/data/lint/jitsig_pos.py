"""C6 positive fixture: every VIOLATION-marked line must be flagged."""
# areal-lint: hot-path (C6 fixture: jitted callables live here)

import jax


def _decode(params, tokens, n, key_window):
    return tokens


class Engine:
    def __init__(self):
        self.max_seq_len = 256
        self.bucket = 16
        self._decode_fn = jax.jit(_decode, static_argnums=(3,))

    def bad_literal(self, tokens):
        return self._decode_fn(None, tokens, 4, 100)  # VIOLATION off-ladder

    def bad_arith(self, tokens, span):
        kw = span + 4
        return self._decode_fn(None, tokens, 4, kw)  # VIOLATION off-ladder

    def bad_len(self, tokens):
        return self._decode_fn(None, tokens, 4, len(tokens))  # VIOLATION

    def helper(self, tokens, kw):
        return self._decode_fn(None, tokens, 4, kw)  # VIOLATION (caller)

    def caller(self, tokens, span):
        # the unsafe value flows through helper's parameter
        return self.helper(tokens, span * 2)
