"""C8 positive fixture: every cross-process drift class the payload
checker must catch, against the fixture registry (WIRE_DOC in
test_lint.py: /ping request {x required, opt}, response {y required})."""

from aiohttp import web


class PingServer:
    async def ping(self, request):
        body = await request.json()
        ghost = body["ghost"]  # VIOLATION: hard read, no producer writes it
        x = body.get("x", 0)  # VIOLATION: silent default on a required key
        return web.json_response({"y": x + ghost})

    def make_app(self):
        app = web.Application()
        app.router.add_post("/ping", self.ping)
        return app


async def call_ping_extra(session, addr):
    resp = await session.post(
        f"http://{addr}/ping",
        json={"x": 1, "bogus": 2},  # VIOLATION: key not in the contract
    )
    data = await resp.json()
    return data["zzz"]  # VIOLATION: response key no handler produces


async def call_ping_missing(session, addr):
    # VIOLATION: closed literal omits the required key 'x'
    await session.post(f"http://{addr}/ping", json={"opt": "o"})
