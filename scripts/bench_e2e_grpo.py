"""End-to-end GRPO benchmark: async vs sync, trajectories/sec/chip.

VERDICT r3 next-step #1 (second half) — THE system's primary metric
(BASELINE.json: "Async GRPO trajectories/sec/chip").  The REAL loop runs
on the chip: generation engine + rollout workflows + reward pool + PPO
trainer + per-step weight publish, in two modes over the same workload:

- **sync**: rollout_batch (generate-all, then train, then publish) — the
  classic alternating loop;
- **async**: WorkflowExecutor.prepare_batch keeps the rollout pipeline
  saturated under the staleness gate (max_head_offpolicyness) while the
  trainer consumes; weight publishes interrupt generation mid-flight and
  clients resume with accumulated tokens (the interruptible-generation
  machinery, blog/AReaL_v0_3.md:203-207).

Single-chip regime: trainer and serving engine share the chip in one
process (0.6B model — both fit), weights hand over in memory.  The async
win measured here comes from pipeline overlap (host-side scheduling,
reward computation, batch assembly, straggler absorption), not from
disaggregated hardware — the multi-host deployment adds that on top.

Prints ONE JSON line:
  {"sync": {...}, "async": {...},
   "async_over_sync_trajs_per_sec": R, "pause_window_s": {...}}
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.obs.trace import dist_summary  # noqa: E402 (stdlib-only)


class _LatencyRecorder:
    """Collects per-request client latencies (ModelResponse.latency /
    .ttft) across a measured mode so the bench reports p50/p99
    distributions instead of single-number means (ISSUE 14)."""

    def __init__(self):
        self.samples = []
        self._mark = 0

    def reset(self):
        self.samples = []
        self._mark = 0

    def mark(self):
        # Warmup boundary: prefer samples completed after this point.  The
        # pre-mark ones stay as a fallback — prepare_batch keeps batches in
        # flight, so a short smoke run can consume only episodes whose
        # generation finished during warmup, and a destructive reset here
        # would leave the measured window with zero samples.
        self._mark = len(self.samples)

    def record(self, resp):
        self.samples.append((
            float(resp.latency),
            float(resp.ttft),
            int(resp.output_len),
        ))

    def summary(self):
        post = self.samples[self._mark:]
        use = post or self.samples
        if not use:
            return None
        e2e = [s[0] for s in use if s[0] != float("inf")]
        ttft = [s[1] for s in use if s[1] != float("inf")]
        itl = [
            (lat - tf) / (n - 1)
            for lat, tf, n in use
            if lat != float("inf") and tf != float("inf") and n > 1
        ]
        return {
            "n": len(use),
            "includes_warmup": not post,
            "e2e_s": dist_summary(e2e),
            "ttft_s": dist_summary(ttft),
            "inter_token_s": dist_summary(itl),
        }


class _RecordingEngine:
    """Transparent engine proxy: forwards everything, taps agenerate."""

    def __init__(self, inner, recorder):
        self._inner = inner
        self._recorder = recorder

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def agenerate(self, req):
        resp = await self._inner.agenerate(req)
        self._recorder.record(resp)
        return resp


class _RecordingWorkflow:
    """Workflow wrapper interposing the recording engine.  Works for
    every transport x mode combination because both WorkflowExecutor
    and rollout_batch drive episodes through
    ``workflow.arun_episode(engine, data)``."""

    def __init__(self, inner, recorder):
        self._inner = inner
        self._recorder = recorder

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def arun_episode(self, engine, data):
        return await self._inner.arun_episode(
            _RecordingEngine(engine, self._recorder), data)


def _reward_any_even(prompt, completions, prompt_ids, completion_ids, **kw):
    """Module-level so the reward process pool can pickle it."""
    return float(any(t % 2 == 0 for t in completion_ids))


def _reward_mt(prompt, completions, prompt_ids, completion_ids, **kw):
    """Multi-turn grader: ~1/3 of turns "solve" the task, so episodes span
    1..max_turns turns — the variable-horizon agentic regime (3 of the 5
    BASELINE.json target configs are multi-turn/agentic)."""
    return float(sum(completion_ids) % 3 == 0)


class _FakeTokenizer:
    """Just enough surface for MultiTurnWorkflow on synthetic token data."""

    def decode(self, tokens):
        return " ".join(str(t) for t in tokens)

    def encode(self, text, add_special_tokens=False):
        return [3] * 6  # fixed-size feedback suffix

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            tokenize=True):
        raise NotImplementedError("bench feeds raw input_ids")


def _make_parts(model_scale: str, n_slots: int, max_seq_len: int,
                group_size: int, batch_norm: bool = False,
                serving_engine: bool = True, share_prefix: bool = True,
                layer_group_size: int = 1, remat_policy: str = "full",
                lm_head_chunk: int = 0, num_layers: int = 0):
    import jax

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.colocated import ColocatedEngine
    from areal_tpu.engine.ppo import JaxPPOActor
    from areal_tpu.models.model_config import qwen2_0p6b_ctx, tiny_config

    if model_scale == "0p6b":
        cfg = qwen2_0p6b_ctx()
    else:  # tiny smoke mode for CPU validation
        cfg = tiny_config(vocab_size=512, qkv_bias=True,
                          hf_architecture="Qwen2ForCausalLM")
    cfg = cfg.replace(eos_token_id=None)
    if num_layers:
        # depth override so the two-level scan A/B can group tiny (2-layer
        # default) models: --num-layers 4 --layer-group-size 4
        cfg = cfg.replace(num_layers=num_layers)

    actor = JaxPPOActor(
        PPOActorConfig(
            experiment_name="e2e-bench", trial_name="b",
            init_from_scratch=True,
            dtype="bfloat16" if model_scale == "0p6b" else "float32",
            param_dtype="bfloat16" if model_scale == "0p6b" else "float32",
            gradient_checkpointing=True,
            remat_policy=remat_policy,
            layer_group_size=layer_group_size,
            lm_head_chunk=lm_head_chunk,
            mesh=MeshConfig(),
            mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=1e-6, warmup_steps_proportion=0.0),
            pack_length_quantum=256,
            max_pack_length=max_seq_len,
            group_size=group_size,
            ppo_n_minibatches=1,
            use_decoupled_loss=True,
            recompute_logprob=True,
            async_stats=True,
            adv_norm=(
                # multi-turn episodes yield ONE trajectory each: normalise
                # over the batch, not fixed-size groups
                NormConfig(mean_level="batch", std_level="batch")
                if batch_norm
                else NormConfig(mean_level="group", std_level="group",
                                group_size=group_size)
            ),
        ),
        model_config=cfg.replace(
            dtype="bfloat16" if model_scale == "0p6b" else "float32",
            param_dtype="bfloat16" if model_scale == "0p6b" else "float32",
        ),
    )
    actor.initialize(ft_spec=FinetuneSpec(1, 4096, 8))

    if not serving_engine:  # remote transport builds its own GenServer
        return actor, None, cfg
    serving = ColocatedEngine(
        cfg.replace(
            dtype="bfloat16" if model_scale == "0p6b" else "float32",
            param_dtype="bfloat16" if model_scale == "0p6b" else "float32",
            remat=False,
        ),
        params=actor._export_params(),
        n_slots=n_slots,
        max_seq_len=max_seq_len,
        prompt_bucket=128,
        decode_chunk=8,
        share_prefix=share_prefix,
    )
    return actor, serving, cfg


def _make_remote_parts(args, actor, cfg):
    """The REAL fleet slice on one chip: a GenServer over HTTP (in-process
    aiohttp thread — two OS processes cannot share the TPU) driven by
    RemoteJaxEngine, with weight publishes streamed as binary chunks +
    device-staged + committed over /update_weights_chunk — the transfer
    choreography the disaggregated deployment uses
    (VERDICT r4 #2: the fleet path had integration tests but no
    trajectories/sec figure)."""
    import asyncio
    import threading

    from aiohttp import web

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.gen.server import GenServer
    from areal_tpu.utils import network

    dtype = "bfloat16" if args.model == "0p6b" else "float32"
    engine = GenEngine(
        cfg.replace(dtype=dtype, param_dtype=dtype, remat=False),
        params=actor._export_params(),
        n_slots=args.n_slots,
        max_seq_len=args.max_seq_len,
        prompt_bucket=128,
        decode_chunk=8,
        share_prefix=args.share_prefix == "on",
    )
    server = GenServer(engine)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    import urllib.request

    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            )
            break
        except Exception:
            time.sleep(0.1)
    else:
        raise RuntimeError("bench GenServer did not come up")

    addr = f"127.0.0.1:{port}"
    os.environ["AREAL_LLM_SERVER_ADDRS"] = addr

    def stop():
        server.shutdown.set()
        # park the device-worker before interpreter teardown starts
        # dismantling XLA under its feet (C++ abort at exit otherwise)
        server.worker.join(timeout=10)
        loop.call_soon_threadsafe(loop.stop)

    return engine, server, addr, stop


def _measure_loop(mode: str, actor, get_batch, publish, steps: int,
                  warmup: int, label: str = "", recorder=None):
    """The shared timed region of every transport x mode combination:
    rollout -> train -> version bump -> publish, with warmup reset and the
    same stats dict — so the colocated/remote A/B can never silently
    measure different things."""
    trajs = tokens = 0
    pauses = []
    rewards = []
    step_stats = []  # per-step PendingTrainStats, materialised after flush
    t_start = None
    if recorder is not None:
        recorder.reset()
    for step in range(warmup + steps):
        if step == warmup:
            import jax

            jax.block_until_ready(actor.params)
            trajs = tokens = 0
            pauses = []
            rewards = []
            if recorder is not None:
                recorder.mark()  # warmup requests must not skew p99s
            t_start = time.perf_counter()
        batch = get_batch()
        trajs += int(np.asarray(batch["attention_mask"]).shape[0])
        tokens += _batch_tokens(batch)
        rewards.append(float(np.asarray(batch["rewards"]).mean()))
        step_stats.append(_train_consume(actor, batch))
        pauses.append(publish())
        print(f"{label}{mode} step {step}: trajs={trajs} tokens={tokens}",
              file=sys.stderr, flush=True)
    import jax

    actor.flush_stats()
    jax.block_until_ready(actor.params)
    wall = time.perf_counter() - t_start
    latency = recorder.summary() if recorder is not None else None
    # per-step training trajectory INCLUDING warmup steps (every step moves
    # the params, so this is the full optimisation path) — the CI two-level-
    # scan A/B gates on these being identical across layer_group_size
    # values.  Group-centred advantages make the step-0 PG loss exactly 0
    # regardless of params, so entropy/new_logp (which see the real forward
    # pass) ride along as the non-degenerate signal.
    def _traj(key):
        return [round(sum(float(st[key]) for st in step), 8)
                for step in step_stats]
    return {
        "loss_trajectory": _traj("loss"),
        "entropy_trajectory": _traj("entropy"),
        "new_logp_trajectory": _traj("new_logp"),
        "latency": latency,
        "steps": steps,
        "trajectories": trajs,
        "effective_tokens": tokens,
        "wall_s": round(wall, 2),
        "trajs_per_sec_per_chip": round(trajs / wall, 3),
        "effective_tokens_per_sec_per_chip": round(tokens / wall, 1),
        "pause_window_s_mean": round(float(np.mean(pauses)), 3),
        # the quality half's raw signal (meaningful for --dataset
        # gsm8k-synth, where the reward is the real math grader)
        "reward_mean": round(float(np.mean(rewards)), 4),
    }


def run_mode_remote(mode: str, actor, client, server_engine, meta, workflow,
                    dataset, batch_size: int, steps: int, warmup: int = 1,
                    recorder=None):
    """Fleet-path counterpart of run_mode: rollouts over HTTP via the
    client's executor, publishes via the trainer's stage+commit transfer
    choreography (live or abort per meta.live_commit)."""
    from areal_tpu.utils.dataloader import StatefulDataLoader

    dataloader = StatefulDataLoader(dataset, batch_size=batch_size, seed=0)
    data_iter = iter(np.random.default_rng(1).permutation(len(dataset)))

    def get_batch():
        if mode == "async":
            return client.prepare_batch(dataloader, workflow=workflow)
        items = [dataset[int(next(data_iter)) % len(dataset)]
                 for _ in range(batch_size)]
        return client.rollout_batch(items, workflow=workflow)

    state = {"version": server_engine.version}

    def publish():
        # the fleet publish: stream + device-stage while generation keeps
        # running, then commit (live = no abort; abort mode exercises the
        # interruption-resume storm)
        state["version"] += 1
        actor.set_version(state["version"])
        actor.stage_weights(meta)
        actor.update_weights(meta)
        client.set_version(state["version"])
        return float(server_engine.last_pause_s)

    return _measure_loop(mode, actor, get_batch, publish, steps, warmup,
                         label="remote ", recorder=recorder)


def run_recoverable(args, actor, client, workflow, dataset):
    """Crash-safe loop (ISSUE 15): per-step atomic recover generations +
    disk weight publishes, resumable across SIGKILL via AREAL_RUN_ID —
    the launchers' relaunch contract, runnable standalone in CI.  Each
    completed step appends one line to ``{recover_dir}/steps.jsonl``
    ({run_id, global_step, version, ledger, ledger_ok}) and rewrites
    ``events_run{run_id}.jsonl``, so a kill at ANY instant leaves enough
    evidence to gate step continuity and ledger invariants on."""
    from areal_tpu.api.config import RecoverConfig
    from areal_tpu.api.io_struct import StepInfo, WeightUpdateMeta
    from areal_tpu.utils import telemetry
    from areal_tpu.utils.dataloader import StatefulDataLoader
    from areal_tpu.utils.faults import (
        arm_fault_point,
        fault_point,
        kill_trainer_at_step,
    )
    from areal_tpu.utils.recover import (
        RecoverHandler,
        check_if_recover,
        config_fingerprint,
    )
    from areal_tpu.utils.shutdown import PreemptionGuard, preempt_exit

    # SIGTERM/SIGINT -> force-dump + RESUME_EXIT_CODE at the step boundary
    guard = PreemptionGuard().install()
    run_id = int(os.environ.get("AREAL_RUN_ID", 0))
    os.makedirs(args.recover_dir, exist_ok=True)
    meta = WeightUpdateMeta.from_disk("e2e-bench", "recover", args.recover_dir)
    rcfg = RecoverConfig(mode="fault", experiment_name="e2e-bench",
                         trial_name="recover", fileroot=args.recover_dir)
    recover = RecoverHandler(rcfg, fingerprint=config_fingerprint({
        "model": args.model, "batch_size": args.batch_size,
        "group_size": args.group_size, "workflow": args.workflow,
        "max_new_tokens": args.max_new_tokens,
    }))
    dataloader = StatefulDataLoader(dataset, batch_size=args.batch_size,
                                    seed=0)
    start_step = 0
    if check_if_recover(rcfg, run_id=run_id):
        info = recover.load(actor, dataloader=dataloader,
                            inference_engine=client,
                            weight_update_meta=meta)
        if info is not None:
            start_step = info.recover_start.global_step
            print(f"recovered: resuming run {run_id} at step {start_step}",
                  file=sys.stderr, flush=True)
    if args.kill_at_step >= start_step:
        kill_trainer_at_step(args.kill_at_step, start_step)
    if args.kill_mid_dump_at_step >= start_step:
        arm_fault_point("recover_mid_dump",
                        at_hit=args.kill_mid_dump_at_step - start_step + 1)

    steps_log = os.path.join(args.recover_dir, "steps.jsonl")
    events_path = os.path.join(args.recover_dir,
                               f"events_run{run_id}.jsonl")
    for global_step in range(start_step, args.steps):
        batch = client.prepare_batch(dataloader, workflow=workflow)
        _train_consume(actor, batch)
        version = global_step + 1
        actor.set_version(version)
        actor.update_weights(meta)  # disk: self-stages snapshot v{version}
        client.update_weights(meta)
        client.set_version(version)
        step_info = StepInfo(epoch=0, epoch_step=global_step,
                             global_step=global_step,
                             steps_per_epoch=args.steps)
        recover.dump(actor, step_info, dataloader=dataloader,
                     inference_engine=client)
        stat = client.executor.staleness_manager.get_stats()
        line = {
            "run_id": run_id,
            "global_step": global_step,
            "version": version,
            "ledger": {
                "submitted": int(stat.submitted),
                "accepted": int(stat.accepted),
                "rejected": int(stat.rejected),
                "running": int(stat.running),
            },
            "ledger_ok": (
                stat.submitted == stat.accepted + stat.rejected + stat.running
                and stat.running >= 0
            ),
        }
        with open(steps_log, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if telemetry.is_enabled():
            # rewrite the full ring each step: intact at whatever step the
            # kill lands
            telemetry.EVENTS.dump_jsonl(events_path)
        print(f"recover run{run_id} step {global_step} done "
              f"(version {version})", file=sys.stderr, flush=True)
        if guard.requested:
            # the step just dumped is the resume point: zero steps lost
            preempt_exit(recover, actor, step_info,
                         rollout_engines=(client,),
                         dump_kwargs={"dataloader": dataloader,
                                      "inference_engine": client})
        fault_point("train_step")
    return {
        "run_id": run_id,
        "start_step": start_step,
        "steps_completed": args.steps - start_step,
        "steps_jsonl": steps_log,
        "events_jsonl": events_path,
    }


def _train_consume(actor, batch):
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    stats = actor.ppo_update(batch)
    return stats


def _batch_tokens(batch) -> int:
    return int(np.asarray(batch["attention_mask"]).sum())


def plan_warm_shapes(args, dataset, actor):
    """Dry-run the packer over sampled step batches to enumerate the
    (rows, row_len) signatures the loop will hit, so warm_shapes can
    AOT-compile them before the timed region (varying rollout lengths
    otherwise recompile INSIDE the loop — ~30-60 s per signature on a
    tunneled chip, which sank the first heterogeneous-length run).

    The packing parameters (quantum, max length, rows multiple) are DERIVED
    from the live actor so the planned signatures match what
    `_prepare_rows` (engine/jax_train.py) actually compiles."""
    from areal_tpu.utils.data import pack_into_rows
    from areal_tpu.utils.datapack import round_up_to_bucket

    quantum = actor.config.pack_length_quantum
    max_len = actor.config.max_pack_length
    dp = (actor.mesh.shape["dp"] * actor.mesh.shape["fsdp"]
          * actor.mesh.shape.get("ep", 1))
    rows_multiple = actor.config.mb_spec.n_mbs * dp
    rng = np.random.default_rng(7)
    fb = len(_FakeTokenizer().encode(""))  # feedback suffix length
    shapes = set()
    for _ in range(8 if args.workflow == "rlvr" else 32):
        idx = rng.choice(len(dataset), args.batch_size, replace=False)
        lens = []
        for i in idx:
            if args.workflow == "multi_turn":
                # one trajectory per episode; length grows per retry turn
                t = int(rng.integers(1, args.max_turns + 1))
                lens.append(args.prompt_len + t * args.max_new_tokens
                            + (t - 1) * fb)
            else:
                budget = dataset[int(i)].get("max_new_tokens",
                                             args.max_new_tokens)
                lens.extend([args.prompt_len + budget] * args.group_size)
        row_len = round_up_to_bucket(max(lens), quantum, max_len)
        mask = np.zeros((len(lens), max(lens)), bool)
        for r, n in enumerate(lens):
            mask[r, :n] = True
        rp = pack_into_rows({"attention_mask": mask}, row_len,
                            rows_multiple=rows_multiple,
                            rows_bucket_pow2=True)
        shapes.add((rp.n_rows, row_len))
    return sorted(shapes)


def run_mode(mode: str, actor, serving, workflow, dataset, batch_size: int,
             steps: int, warmup: int = 1, interrupt_publish: bool = False,
             recorder=None):
    """-> {trajs_per_sec, effective_tokens_per_sec, steps, pause_s_mean}"""
    from areal_tpu.api.config import InferenceEngineConfig
    from areal_tpu.core.executor import WorkflowExecutor
    from areal_tpu.utils.dataloader import StatefulDataLoader

    executor = None
    if mode == "async":
        executor = WorkflowExecutor(
            InferenceEngineConfig(
                experiment_name="e2e-bench", trial_name="b",
                consumer_batch_size=batch_size,
                max_concurrent_rollouts=batch_size * 2,
                max_head_offpolicyness=4,
                request_timeout=600,
            ),
            serving,
        )
        executor.initialize()
        dataloader = StatefulDataLoader(dataset, batch_size=batch_size, seed=0)

    data_iter = iter(np.random.default_rng(1).permutation(len(dataset)))

    def get_batch():
        if mode == "async":
            return executor.prepare_batch(dataloader, workflow=workflow)
        items = [dataset[int(next(data_iter)) % len(dataset)]
                 for _ in range(batch_size)]
        return serving.rollout_batch(items, workflow=workflow)

    state = {"version": serving.get_version()}

    def publish():
        # device-to-device handoff: both sides share the chip, so the
        # publish never touches the host (export_device_params); the
        # executor reads the new version via serving.get_version()
        state["version"] += 1
        actor.set_version(state["version"])
        return serving.update_weights_in_memory(
            actor.export_device_params(), state["version"],
            interrupt=interrupt_publish,
        )

    try:
        return _measure_loop(mode, actor, get_batch, publish, steps, warmup,
                             recorder=recorder)
    finally:
        if executor is not None:
            executor.destroy()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="0p6b", choices=["0p6b", "tiny"])
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--group-size", type=int, default=2)
    p.add_argument("--n-slots", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--prompt-len", type=int, default=64)
    p.add_argument("--max-new-tokens", type=int, default=128)
    p.add_argument("--modes", default="sync,async")
    p.add_argument("--layer-group-size", type=int, default=1,
                   help="two-level layer scan: layers per remat group "
                   "(TrainEngineConfig.layer_group_size); must divide the "
                   "model depth, 1 = classic per-layer scan")
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "dots", "save_attn", "save_mlp",
                            "carry_offload"],
                   help="per-group remat rung "
                   "(TrainEngineConfig.remat_policy)")
    p.add_argument("--lm-head-chunk", type=int, default=0,
                   help="fused LM-head vocab chunk width "
                   "(TrainEngineConfig.lm_head_chunk); 0 = env default")
    p.add_argument("--num-layers", type=int, default=0,
                   help="model depth override (0 = model default) — lets "
                   "the tiny 2-layer CPU config run grouped-scan A/Bs at "
                   "--layer-group-size 4")
    p.add_argument("--warmup", type=int, default=1,
                   help="untimed leading steps; interrupt-publish runs want "
                        "2 so the first post-publish abort storm (whose "
                        "burst admission compiles NEW suffix-prefill "
                        "signatures) stays outside the timed region")
    p.add_argument("--workflow", default="rlvr",
                   choices=["rlvr", "multi_turn"],
                   help="multi_turn = retry-until-correct agentic episodes "
                        "(variable turn count; exercises KV prefix reuse)")
    p.add_argument("--max-turns", type=int, default=3)
    p.add_argument("--len-jitter", type=float, default=0.0,
                   help=">0 gives each prompt a log-uniform generation "
                        "budget in [max_new/(1+j), max_new] — length "
                        "variance a la real math workloads")
    p.add_argument("--publish-mode", default="live",
                   choices=["live", "interrupt", "abort"],
                   help="live = non-aborting swap_weights_live (the "
                        "default everywhere since r5); interrupt/abort "
                        "(synonyms) = abort-and-resume for A/B comparison")
    p.add_argument("--share-prefix", default="on", choices=["on", "off"],
                   help="off = pre-fan-out admission (per-slot retained "
                        "reuse only) for A/B regression runs")
    p.add_argument("--transport", default="colocated",
                   choices=["colocated", "remote"],
                   help="colocated = in-process ColocatedEngine handoff; "
                        "remote = REAL GenServer over HTTP + RemoteJaxEngine "
                        "+ transfer-mode weight publish (the fleet slice)")
    p.add_argument("--chaos", action="store_true",
                   help="mount a seeded FaultProxy (utils/faults.py) "
                        "between the client and the gen server: HTTP 500s, "
                        "latency spikes, and mid-request disconnects replay "
                        "deterministically from --chaos-seed; reports "
                        "goodput + trajectory-loss fraction under fire. "
                        "Requires --transport remote and async-only --modes")
    p.add_argument("--recover-dir", default="",
                   help="run the crash-safe recoverable loop (ISSUE 15) "
                        "instead of the timed A/B: per-step atomic recover "
                        "generations + disk weight publishes under this "
                        "dir, resumable across SIGKILL via AREAL_RUN_ID. "
                        "Requires --transport remote and async-only --modes")
    p.add_argument("--kill-at-step", type=int, default=-1,
                   help="with --recover-dir: SIGKILL self (no flush) at the "
                        "END of this global step — the trainer-kill chaos "
                        "fault (utils/faults.py kill_trainer_at_step)")
    p.add_argument("--kill-mid-dump-at-step", type=int, default=-1,
                   help="with --recover-dir: SIGKILL self INSIDE this "
                        "step's recover dump, between the staging fsync "
                        "and the atomic rename (fault point "
                        "recover_mid_dump) — the torn-checkpoint case")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="one integer reproduces the exact injected-failure "
                        "sequence (FaultPlan.generate)")
    p.add_argument("--chaos-rate", type=float, default=0.15,
                   help="per-call fault probability in the generated plan")
    p.add_argument("--telemetry-dir", default="",
                   help="enable unified telemetry (utils/telemetry.py) and "
                        "dump events.jsonl + trace.json (Perfetto) + "
                        "metrics.json registry snapshots here; also starts "
                        "a trainer-side /metrics endpoint")
    p.add_argument("--xla-profile-dir", default="",
                   help="wrap the measured mode loop in a jax.profiler "
                        "trace (utils/profiling.py profile_trace)")
    p.add_argument("--dataset", default="random",
                   choices=["random", "gsm8k-synth"],
                   help="random = synthetic token prompts (throughput "
                        "measurement); gsm8k-synth = the synthetic GSM8K "
                        "generator + WordTokenizer + the REAL "
                        "gsm8k_reward_fn (dataset/gsm8k_synth.py) — the "
                        "quality-half workload, learnable rewards included")
    args = p.parse_args()
    interrupt_publish = args.publish_mode in ("interrupt", "abort")
    if args.dataset == "gsm8k-synth" and args.workflow != "rlvr":
        p.error("--dataset gsm8k-synth runs the RLVR workflow (its reward "
                "parses \\boxed{} answers, not multi-turn feedback)")
    if args.chaos:
        if args.transport != "remote":
            p.error("--chaos requires --transport remote (faults are "
                    "injected at the HTTP boundary)")
        if any(m != "async" for m in args.modes.split(",")):
            p.error("--chaos runs async modes only: a sync rollout_batch "
                    "waits for its exact batch, so one lost trajectory "
                    "hangs the step; prepare_batch keeps consuming")
    if args.recover_dir:
        if args.transport != "remote":
            p.error("--recover-dir requires --transport remote (the fleet "
                    "slice: gen server rejoin + pinned disk reload is the "
                    "machinery under test)")
        if any(m != "async" for m in args.modes.split(",")):
            p.error("--recover-dir runs async modes only (the recover "
                    "harness snapshots the executor's staleness ledger)")
        if args.chaos:
            p.error("--recover-dir and --chaos are separate harnesses; "
                    "run them in separate invocations")
    elif args.kill_at_step >= 0 or args.kill_mid_dump_at_step >= 0:
        p.error("--kill-at-step/--kill-mid-dump-at-step require "
                "--recover-dir")
    if args.workflow == "multi_turn" and args.len_jitter > 0:
        # MultiTurnWorkflow generates with its fixed gconfig budget; per-item
        # budgets would be ignored and the result JSON would claim a
        # jittered regime that never ran.  Turn variance already provides
        # the length distribution in this mode.
        p.error("--len-jitter is not supported with --workflow multi_turn")

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # the baked TPU plugin forces jax_platforms at interpreter boot;
        # re-apply the env choice so CPU smoke runs stay off the chip
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from areal_tpu.utils import telemetry

    train_metrics_port = None
    if args.telemetry_dir:
        # enable BEFORE any engine/workflow is built so lifecycle events
        # from warmup onward land in the log
        os.makedirs(args.telemetry_dir, exist_ok=True)
        telemetry.set_enabled(True)
        _, train_metrics_port = telemetry.start_metrics_server(telemetry.TRAIN)
        print(f"trainer /metrics on :{train_metrics_port}",
              file=sys.stderr, flush=True)
    elif args.recover_dir:
        # the recover harness's step-continuity gate consumes the stitched
        # lifecycle log, so events must flow even without --telemetry-dir
        telemetry.set_enabled(True)

    from areal_tpu.api.config import GenerationHyperparameters
    from areal_tpu.api.reward import prewarm_reward_pool
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    actor, serving, cfg = _make_parts(
        args.model, args.n_slots, args.max_seq_len, args.group_size,
        batch_norm=args.workflow == "multi_turn",
        serving_engine=args.transport == "colocated",
        share_prefix=args.share_prefix == "on",
        layer_group_size=args.layer_group_size,
        remat_policy=args.remat_policy,
        lm_head_chunk=args.lm_head_chunk,
        num_layers=args.num_layers,
    )
    client = server_engine = stop_server = meta = None
    chaos_plan = chaos_proxy = None
    if args.transport == "remote":
        from areal_tpu.api.config import InferenceEngineConfig
        from areal_tpu.api.io_struct import WeightUpdateMeta
        from areal_tpu.engine.jax_remote import RemoteJaxEngine

        server_engine, _server, addr, stop_server = _make_remote_parts(
            args, actor, cfg
        )
        client_addr = addr
        if args.chaos:
            from areal_tpu.utils.faults import FaultPlan, FaultProxy

            # generate() excludes "hang" by default — a held request would
            # stall the run for the full client timeout, which measures the
            # timeout constant, not the failover machinery
            chaos_plan = FaultPlan.generate(
                seed=args.chaos_seed,
                n_calls=args.batch_size * (args.warmup + args.steps) * 8,
                rate=args.chaos_rate,
            )
            chaos_proxy = FaultProxy(addr, chaos_plan)
            client_addr = chaos_proxy.start()
            # the client talks through the proxy; the trainer's transfer
            # publish goes straight to the real server via
            # AREAL_LLM_SERVER_ADDRS (set in _make_remote_parts), so weight
            # chunks are not subject to generation-path faults
            print(f"chaos proxy on {client_addr} -> {addr} "
                  f"(seed={args.chaos_seed}, {len(chaos_plan.plan)} faults "
                  f"planned)", file=sys.stderr, flush=True)
        client = RemoteJaxEngine(InferenceEngineConfig(
            experiment_name="e2e-bench", trial_name="b",
            consumer_batch_size=args.batch_size,
            max_concurrent_rollouts=args.batch_size * 2,
            max_head_offpolicyness=4,
            request_timeout=600,
        ))
        client.initialize(addr=client_addr)
        meta = WeightUpdateMeta.from_transfer(
            "e2e-bench", "b", chunk_mb=64,
            live_commit=not interrupt_publish,
        )
    prewarm_reward_pool()
    if args.workflow == "multi_turn":
        from areal_tpu.workflow.multi_turn import MultiTurnWorkflow

        workflow = MultiTurnWorkflow(
            reward_fn=_reward_mt,
            gconfig=GenerationHyperparameters(
                n_samples=1,
                max_new_tokens=args.max_new_tokens,
                temperature=1.0,
            ),
            tokenizer=_FakeTokenizer(),
            max_turns=args.max_turns,
        )
    elif args.dataset == "gsm8k-synth":
        # the quality-half workload (dataset/gsm8k_synth.py): real word
        # problems through the closed-vocabulary tokenizer, scored by the
        # REAL math reward — rewards are learnable, not coin flips
        from areal_tpu.dataset.gsm8k_synth import (
            WordTokenizer,
            generate_problems,
        )
        from areal_tpu.reward.math_parser import gsm8k_reward_fn

        synth_tok = WordTokenizer()
        assert len(synth_tok) <= cfg.vocab_size, (
            f"model vocab {cfg.vocab_size} < tokenizer {len(synth_tok)}"
        )
        workflow = RLVRWorkflow(
            reward_fn=gsm8k_reward_fn,
            gconfig=GenerationHyperparameters(
                n_samples=args.group_size,
                max_new_tokens=args.max_new_tokens,
                temperature=1.0,
            ),
            tokenizer=synth_tok,
        )
    else:
        workflow = RLVRWorkflow(
            reward_fn=_reward_any_even,
            gconfig=GenerationHyperparameters(
                n_samples=args.group_size,
                max_new_tokens=args.max_new_tokens,
                temperature=1.0,
            ),
        )
    # per-request latency distributions (TTFT / inter-token / e2e) come
    # from a transparent workflow wrapper; transport-agnostic because
    # every episode path funnels through workflow.arun_episode
    recorder = _LatencyRecorder()
    workflow = _RecordingWorkflow(workflow, recorder)
    rng = np.random.default_rng(0)
    dataset = []
    if args.dataset == "gsm8k-synth":
        for prob in generate_problems(256, seed=0):
            dataset.append({
                "input_ids": synth_tok.apply_chat_template(
                    prob["messages"], add_generation_prompt=True
                ),
                "query_id": prob["query_id"],
                "answer": prob["answer"],
            })
        # warm-shape planning sizes rows from args.prompt_len; cover the
        # longest generated problem so the packer's signatures match
        args.prompt_len = max(len(d["input_ids"]) for d in dataset)
    else:
        for i in range(256):
            item = {
                "input_ids": rng.integers(0, cfg.vocab_size,
                                          args.prompt_len).tolist(),
                "query_id": str(i),
            }
            if args.len_jitter > 0:
                # realistic length variance (the reference's math workloads
                # span 1k-31k generated tokens): log-uniform budgets in
                # [max_new/(1+j), max_new].  Sync pays the straggler tail
                # every step; async absorbs it — this is the regime the
                # async design targets.
                lo = args.max_new_tokens / (1.0 + args.len_jitter)
                item["max_new_tokens"] = int(np.exp(
                    rng.uniform(np.log(lo), np.log(args.max_new_tokens))
                ))
            dataset.append(item)
    shapes = plan_warm_shapes(args, dataset, actor)
    print(f"warming {len(shapes)} pack signatures: {shapes}",
          file=sys.stderr, flush=True)
    t_warm = time.perf_counter()
    actor.warm_shapes(shapes)
    warm_s = round(time.perf_counter() - t_warm, 1)
    print(f"warm done in {warm_s}s", file=sys.stderr, flush=True)

    result = {
        "model": args.model,
        "workflow": args.workflow,
        "transport": args.transport,
        "dataset": args.dataset,
        "device_kind": jax.devices()[0].device_kind,
        "batch_size": args.batch_size,
        "group_size": args.group_size,
        "max_new_tokens": args.max_new_tokens,
        "len_jitter": args.len_jitter,
        "publish_mode": args.publish_mode,
        "share_prefix": args.share_prefix,
        # the scan shape actually compiled (ISSUE 20): group size from the
        # post-replace model config, unroll after the loud divisor fallback
        "layer_group_size": int(max(1, actor.model_config.layer_group_size)),
        "effective_scan_unroll": int(
            getattr(actor, "_effective_scan_unroll", 1)),
        "remat_policy": args.remat_policy,
        "lm_head_chunk": args.lm_head_chunk,
        "num_layers": int(actor.model_config.num_layers),
        "warm_shapes": [list(s) for s in shapes],
        "warm_s": warm_s,
    }
    try:
        from contextlib import nullcontext

        prof_ctx = nullcontext()
        if args.xla_profile_dir:
            from areal_tpu.utils.profiling import profile_trace

            prof_ctx = profile_trace(args.xla_profile_dir)
            result["xla_profile_dir"] = args.xla_profile_dir
        with prof_ctx:
            if args.recover_dir:
                result["recover"] = run_recoverable(
                    args, actor, client, workflow, dataset
                )
            else:
                for mode in args.modes.split(","):
                    if args.transport == "remote":
                        result[mode] = run_mode_remote(
                            mode, actor, client, server_engine, meta,
                            workflow, dataset, args.batch_size, args.steps,
                            warmup=args.warmup, recorder=recorder,
                        )
                    else:
                        result[mode] = run_mode(
                            mode, actor, serving, workflow, dataset,
                            args.batch_size, args.steps, warmup=args.warmup,
                            interrupt_publish=interrupt_publish,
                            recorder=recorder,
                        )
        if "sync" in result and "async" in result:
            result["async_over_sync_trajs_per_sec"] = round(
                result["async"]["trajs_per_sec_per_chip"]
                / result["sync"]["trajs_per_sec_per_chip"], 3,
            )
        st = (server_engine if args.transport == "remote"
              else serving.engine).stats
        total_prefill = (st["prefill_tokens"] + st["suffix_tokens"]
                         + st["reused_tokens"] + st["shared_tokens"])
        if args.workflow == "multi_turn":
            # later turns re-prefill only the suffix when the engine still
            # holds the episode's KV prefix (gen/kv_pool.py radix index)
            result["kv_reuse"] = {
                "prefill_tokens": int(st["prefill_tokens"]),
                "suffix_tokens": int(st["suffix_tokens"]),
                "reused_tokens": int(st["reused_tokens"]),
                "reused_fraction": round(
                    st["reused_tokens"] / max(total_prefill, 1), 3
                ),
            }
        if args.group_size > 1:
            # group fan-out prefill: siblings of each GRPO group ride the
            # representative's prefix KV (gen/engine.py cluster fan-out)
            result["shared_prefill"] = {
                "prefill_tokens": int(st["prefill_tokens"]),
                "suffix_tokens": int(st["suffix_tokens"]),
                "shared_tokens": int(st["shared_tokens"]),
                "copy_calls": int(st["copy_calls"]),
                "shared_fraction": round(
                    st["shared_tokens"] / max(total_prefill, 1), 3
                ),
            }
        if args.chaos:
            st = client.executor.staleness_manager.get_stats()
            lost = int(client.executor.lost_trajectories)
            result["chaos"] = {
                "seed": args.chaos_seed,
                "rate": args.chaos_rate,
                "plan_size": len(chaos_plan.plan),
                # the replayable record: same seed -> same sequence
                "injected": [list(t) for t in chaos_plan.injected_log()],
                "lost_trajectories": lost,
                "submitted": int(st.submitted),
                "trajectory_loss_fraction": round(
                    lost / max(1, st.submitted), 4
                ),
            }
        if args.telemetry_dir:
            events_path = os.path.join(args.telemetry_dir, "events.jsonl")
            trace_path = os.path.join(args.telemetry_dir, "trace.json")
            snap_path = os.path.join(args.telemetry_dir, "metrics.json")
            n_events = telemetry.EVENTS.dump_jsonl(events_path)
            telemetry.EVENTS.dump_chrome_trace(trace_path)
            with open(snap_path, "w") as f:
                json.dump({
                    "gen": telemetry.GEN.snapshot(),
                    "train": telemetry.TRAIN.snapshot(),
                    "router": telemetry.ROUTER.snapshot(),
                }, f, indent=2, default=str)
            result["telemetry"] = {
                "dir": args.telemetry_dir,
                "events_jsonl": events_path,
                "chrome_trace": trace_path,
                "metrics_snapshot": snap_path,
                "n_events": n_events,
                "dropped_events": telemetry.EVENTS.dropped,
                "trainer_metrics_port": train_metrics_port,
            }
        # the result line must survive teardown hiccups (stale request
        # callbacks etc.) — print FIRST, clean up after
        print(json.dumps(result))
        sys.stdout.flush()
    finally:
        try:
            if client is not None:
                client.destroy()
            if chaos_proxy is not None:
                chaos_proxy.stop()
            if stop_server is not None:
                stop_server()
            if serving is not None:
                serving.destroy()
        except Exception as e:  # noqa: BLE001 — teardown only
            print(f"teardown: {str(e)[:120]}", file=sys.stderr)


if __name__ == "__main__":
    main()
