"""Trace-driven load replay: latency-vs-throughput curves for the fleet.

ROADMAP item 2's measurement rig: fleet claims must be p50/p99
latency-vs-throughput curves under realistic mixed traffic, not
single-run tok/s means.  This harness drives a live router+gen fleet
with a recorded or synthetic arrival process at several rate
multipliers and emits one curve JSON:

- **workload**: either ``--trace events.jsonl`` (replays a recorded
  run's ``rollout_submit`` arrival clock, prompt lengths, and decode
  budgets — see `areal_tpu/obs/workload.py`) or ``--workload mixed``
  (seeded synthetic mix: chat bursts, GRPO groups with shared prompts,
  long-context stragglers);
- **fleet**: self-hosted by default — N in-process GenServers (tiny
  model on CPU, real model on TPU) behind the real Router, the same
  in-process-aiohttp pattern bench_e2e_grpo uses — or an external
  fleet via ``--addr host:port`` (nothing is booted, client-side
  metrics only);
- **rates**: each ``--rates`` multiplier compresses the arrival clock
  (16 = same work arriving 16x faster) and replays the full workload,
  measuring per-request e2e latency, achieved throughput, and errors.

The driver emits client-side lifecycle events (rollout_submit /
gen_done / rollout_lost) into the shared telemetry ring, so a
self-hosted run's ``--telemetry-dir`` dump contains full spans
(admission, prefill, decode chunks included) and ``--slo-report``
turns it straight into an SLO_REPORT JSON for `scripts/check_slo.py`.

Example (CPU smoke, the slo-smoke CI job):

  python scripts/bench_replay.py --model tiny --servers 1 --router \\
      --workload mixed --duration 8 --base-rps 2 --rates 1,4,16 \\
      --n-slots 8 --max-seq-len 256 --max-new-tokens 16 \\
      --telemetry-dir /tmp/replay --slo-report /tmp/replay/SLO_REPORT.json \\
      --out /tmp/replay/curves.json
"""

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.obs import slo as slo_mod  # noqa: E402
from areal_tpu.obs import workload as wl  # noqa: E402
from areal_tpu.obs.trace import dist_summary  # noqa: E402
from areal_tpu.utils import telemetry  # noqa: E402

SCHEMA = "areal-replay-curves/v1"


# ---------------------------------------------------------------------------
# fleet boot (self-hosted mode)
# ---------------------------------------------------------------------------


def _boot_server(cfg, params, args, role: str = "both",
                 host_offload: Optional[bool] = None):
    """One GenServer on its own aiohttp thread (the bench_e2e pattern:
    two OS processes cannot share a chip, so the fleet slice lives in
    threads).  Returns (addr, stop)."""
    import threading

    from aiohttp import web

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.gen.server import GenServer
    from areal_tpu.utils import network

    engine = GenEngine(
        cfg,
        params=params,
        n_slots=args.n_slots,
        max_seq_len=args.max_seq_len,
        prompt_bucket=64,
        decode_chunk=8,
        share_prefix=True,
        host_offload=(args.host_offload
                      if host_offload is None else host_offload),
        host_cache_mb=args.host_cache_mb,
        host_min_tokens=args.host_min_tokens,
    )
    server = GenServer(engine, role=role)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    _wait_health(f"127.0.0.1:{port}")

    def stop():
        server.shutdown.set()
        server.worker.join(timeout=10)
        loop.call_soon_threadsafe(loop.stop)

    return f"127.0.0.1:{port}", stop


def _boot_router(addrs: List[str], disagg: bool = False):
    """The real Router over the booted servers, same thread pattern."""
    import threading

    from aiohttp import web

    from areal_tpu.gen.router import Router, RouterConfig

    router = Router(RouterConfig(disagg=disagg), addresses=list(addrs))
    state: Dict[str, Any] = {}
    started = threading.Event()

    def run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def _serve():
            runner = web.AppRunner(router.app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["loop"] = loop
            state["runner"] = runner
            state["port"] = runner.addresses[0][1]
            started.set()

        loop.run_until_complete(_serve())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    if not started.wait(timeout=30):
        raise RuntimeError("replay Router did not come up")

    def stop():
        async def _cleanup():
            await state["runner"].cleanup()

        asyncio.run_coroutine_threadsafe(
            _cleanup(), state["loop"]).result(timeout=10)
        state["loop"].call_soon_threadsafe(state["loop"].stop)

    return f"127.0.0.1:{state['port']}", stop


def _wait_health(addr: str, timeout: float = 60.0) -> None:
    import urllib.request

    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            urllib.request.urlopen(f"http://{addr}/health", timeout=1)
            return
        except Exception:
            time.sleep(0.1)
    raise RuntimeError(f"replay backend {addr} did not come up")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


async def _drive(addr: str, arrivals: List[wl.Arrival], *, rate: float,
                 vocab: int, seed: int, timeout: float,
                 max_seq_len: int, pin_streams: bool = False,
                 record: bool = False,
                 retries: int = 0) -> List[Dict[str, Any]]:
    """Replay one rate multiplier: fire every arrival at its scheduled
    time (absolute offsets from the run start, so client-side queueing
    delay shows up as latency, exactly like an open-loop load test) and
    measure per-request wall latency.

    ``pin_streams`` assigns a deterministic sampler stream id per
    trace_id (the cross-fleet bit-identity contract: same-seed engines
    share ``_decode_key``, so a client-pinned stream makes the token
    stream a pure function of the request, not of which server — or
    fleet topology — served it).  ``record`` keeps trace_id + token +
    logprob streams on each result for A/B comparison.  ``retries``
    emulates the RemoteInfEngine failover contract: on transport error
    resubmit up to N times (counter-keyed sampling makes the resubmit
    continue the identical stream), and only exhausted retries count as
    lost trajectories."""
    import aiohttp
    import zlib

    scaled = wl.scale(arrivals, rate)
    results: List[Dict[str, Any]] = []
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    conn = aiohttp.TCPConnector(limit=0)
    client_timeout = aiohttp.ClientTimeout(total=timeout)
    async with aiohttp.ClientSession(
            connector=conn, timeout=client_timeout) as session:

        async def one(i: int, a: wl.Arrival) -> None:
            await asyncio.sleep(max(0.0, a.t - (loop.time() - t0)))
            # keep prompt + budget inside the fleet's sequence budget
            budget = max(1, min(a.max_new_tokens, max_seq_len - 4))
            plen = max(1, min(a.prompt_len, max_seq_len - budget - 4))
            ids = wl.prompt_ids(a, vocab=vocab, seed=seed)[:plen]
            trace_id = f"replay-x{rate:g}-{i:05d}"
            payload = {
                "rid": trace_id,
                "trace_id": trace_id,
                "group_id": f"x{rate:g}-{a.group_id}" if a.group_id else "",
                "group_n": a.group_n if a.group_id else 0,
                "input_ids": ids,
                "sampling_params": {
                    "max_new_tokens": budget,
                    "temperature": 1.0,
                },
            }
            if pin_streams:
                payload["stream_id"] = (
                    (zlib.crc32(trace_id.encode()) & 0x0FFFFFFF) + 1)
            telemetry.emit("rollout_submit", trace_id=trace_id,
                           rid=trace_id, group_id=payload["group_id"],
                           input_len=len(ids), server=addr)
            start = time.perf_counter()
            rec: Dict[str, Any] = {"kind": a.kind, "rate": rate}
            attempts = 0
            while True:
                attempts += 1
                try:
                    async with session.post(
                            f"http://{addr}/generate", json=payload) as resp:
                        body = await resp.json()
                        if resp.status != 200:
                            raise RuntimeError(f"HTTP {resp.status}")
                    lat = time.perf_counter() - start
                    out_len = len(body.get("output_tokens", []))
                    telemetry.emit(
                        "gen_done", trace_id=trace_id,
                        stop_reason=body.get("stop_reason", "stop"),
                        output_len=out_len, attempts=attempts, latency_s=lat)
                    rec.update(ok=True, latency_s=lat, output_len=out_len,
                               stop_reason=body.get("stop_reason", "stop"))
                    if record:
                        rec.update(
                            trace_id=trace_id,
                            tokens=list(body.get("output_tokens", [])),
                            logprobs=list(body.get("output_logprobs", [])
                                          or []))
                    break
                except Exception as e:  # noqa: BLE001 — errors are data here
                    if attempts <= retries:
                        telemetry.emit("resubmit", trace_id=trace_id,
                                       attempt=attempts)
                        await asyncio.sleep(0.2)
                        continue
                    lat = time.perf_counter() - start
                    telemetry.emit("rollout_lost", trace_id=trace_id)
                    rec.update(ok=False, latency_s=lat, output_len=0,
                               error=str(e)[:120])
                    break
            results.append(rec)

        await asyncio.gather(*(one(i, a) for i, a in enumerate(scaled)))
    return results


async def _warmup(addrs: List[str], *, vocab: int,
                  max_seq_len: int) -> None:
    """Trigger JIT compilation before measuring: one request per prompt
    bucket count the workload can reach, against EVERY server directly
    (through the router a balancer could leave a replica cold, and its
    compile stall would poison the first measured rate).  Runs with
    telemetry still disabled so compile time never lands in the SLO log
    or the curves."""
    import aiohttp

    lens = sorted({8, min(100, max(9, max_seq_len - 12))})
    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=300)) as session:
        for a, addr in enumerate(addrs):
            for i, plen in enumerate(lens):
                payload = {
                    "rid": f"warmup-{a}-{i}",
                    "trace_id": f"warmup-{a}-{i}",
                    "input_ids": [3 + (j % max(1, vocab - 4))
                                  for j in range(plen)],
                    "sampling_params": {"max_new_tokens": 8,
                                        "temperature": 1.0},
                }
                async with session.post(
                        f"http://{addr}/generate", json=payload) as resp:
                    await resp.json()


def _scrape_prefix_stats(addrs: List[str]) -> Dict[str, int]:
    """Sum the radix/paged prefix-cache counters over the fleet's
    /metrics JSON surfaces (works identically for self-hosted and
    external backends)."""
    import urllib.request

    keys = ("prefix_cache_hits", "prefix_cache_misses",
            "prefix_cache_evictions", "prefix_cache_host_swaps")
    total = dict.fromkeys(keys, 0)
    for addr in addrs:
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5) as r:
                m = json.loads(r.read())
        except Exception:  # noqa: BLE001 — external fleets may not expose it
            continue
        for k in keys:
            total[k] += int(m.get(k, 0))
    return total


def _prefix_cache_delta(before: Dict[str, int],
                        after: Dict[str, int]) -> Dict[str, Any]:
    d = {k: after[k] - before[k] for k in before}
    lookups = d["prefix_cache_hits"] + d["prefix_cache_misses"]
    return {
        "hits": d["prefix_cache_hits"],
        "misses": d["prefix_cache_misses"],
        "evictions": d["prefix_cache_evictions"],
        "host_swaps": d["prefix_cache_host_swaps"],
        "hit_rate": (d["prefix_cache_hits"] / lookups) if lookups else None,
    }


def _rate_summary(rate: float, arrivals: List[wl.Arrival],
                  results: List[Dict[str, Any]],
                  wall_s: float) -> Dict[str, Any]:
    ok = [r for r in results if r["ok"]]
    out_tokens = sum(r["output_len"] for r in ok)
    offered_span = (arrivals[-1].t / rate) if arrivals else 0.0
    return {
        "rate": rate,
        "n": len(results),
        "ok": len(ok),
        "errors": len(results) - len(ok),
        "offered_rps": (len(arrivals) / offered_span)
        if offered_span > 0 else None,
        "achieved_rps": (len(ok) / wall_s) if wall_s > 0 else None,
        "output_tokens": out_tokens,
        "output_tokens_per_s": (out_tokens / wall_s) if wall_s > 0 else None,
        "wall_s": round(wall_s, 3),
        "latency_s": dist_summary(r["latency_s"] for r in ok),
        "latency_by_kind": {
            kind: dist_summary(r["latency_s"] for r in ok
                               if r["kind"] == kind)
            for kind in sorted({r["kind"] for r in ok})
        },
    }


# ---------------------------------------------------------------------------
# disaggregated A/B (ISSUE 17)
# ---------------------------------------------------------------------------


async def _warm_through_router(addr: str, *, vocab: int, n: int = 6) -> None:
    """Through-router warmup: the direct per-server pass compiles the
    fresh-prefill/decode programs, but only a routed request exercises
    the disagg handoff path (leg1 clip, /kv_export, /kv_import, leg2
    suffix-prefill on the decode server).  Run the same pass in BOTH
    phases so the colocated control pays identical compile costs."""
    import aiohttp

    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=300)) as session:
        for i in range(n):
            plen = 12 + 7 * i
            payload = {
                "rid": f"routewarm-{i}",
                "trace_id": f"routewarm-{i}",
                "input_ids": [3 + (j % max(1, vocab - 4))
                              for j in range(plen)],
                "sampling_params": {"max_new_tokens": 6,
                                    "temperature": 1.0},
            }
            async with session.post(
                    f"http://{addr}/generate", json=payload) as resp:
                await resp.json()


def _router_snap(addr: str) -> Dict[str, Any]:
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://{addr}/metrics", timeout=5) as r:
            return json.loads(r.read())
    except Exception:  # noqa: BLE001 — metrics are best-effort evidence
        return {}


def _run_ab(args, p, arrivals: List[wl.Arrival],
            rates: List[float], source: Dict[str, Any]) -> int:
    """Disaggregated-vs-colocated A/B at matched arrival rate.

    Two sequential phases over the SAME workload, seed, and total server
    count: a colocated control (N role=both replicas) and the disagg
    fleet (1 prefill + N-1 decode servers, role-aware router).  Client
    pins sampler stream ids per trace_id, so the two phases must produce
    bit-identical token streams — the exactness gate.  The perf verdict
    is decode-interference elimination: disagg inter-token p99 must not
    exceed the colocated control's.  ``--chaos`` kills the prefill
    server mid-way through the last disagg rate; the driver's failover
    retries (the RemoteInfEngine contract) must recover every
    trajectory for the zero-lost gate."""
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import bench_serving as bs

    cfg, params = bs.serving_model_setup(args.model)
    vocab = cfg.vocab_size
    n_servers = max(3, args.servers)
    phases: Dict[str, Any] = {}
    streams: Dict[str, Dict[str, Dict[str, Any]]] = {}

    for mode in ("colocated", "disagg"):
        stops: List[Any] = []
        chaos_timer: Optional[threading.Timer] = None
        try:
            server_addrs = []
            if mode == "colocated":
                specs = [("both", None)] * n_servers
            else:
                # decode servers need the host tier: /kv_import installs
                # pages as host-tier entries that swap-in re-scatters
                specs = [("prefill", None)] + \
                    [("decode", True)] * (n_servers - 1)
            for role, off in specs:
                a, stop = _boot_server(cfg, params, args, role=role,
                                       host_offload=off)
                server_addrs.append(a)
                stops.append(stop)
            addr, rstop = _boot_router(server_addrs,
                                       disagg=(mode == "disagg"))
            stops.append(rstop)
            print(f"[{mode}] fleet up: {specs} -> {addr}",
                  file=sys.stderr, flush=True)

            asyncio.run(_warmup(server_addrs, vocab=vocab,
                                max_seq_len=args.max_seq_len))
            asyncio.run(_warm_through_router(addr, vocab=vocab))

            telemetry.set_enabled(True)
            telemetry.EVENTS.clear()
            curve = []
            phase_streams: Dict[str, Dict[str, Any]] = {}
            for ri, rate in enumerate(rates):
                last = ri == len(rates) - 1
                chaos_here = args.chaos and mode == "disagg" and last
                retries = 2 if chaos_here else 0
                if chaos_here:
                    span = (arrivals[-1].t / rate) if arrivals else 1.0
                    kill_stop = stops[0]  # the prefill server
                    chaos_timer = threading.Timer(
                        max(0.2, 0.4 * span), kill_stop)
                    chaos_timer.start()
                    print(f"[{mode}] chaos: prefill kill armed at "
                          f"{max(0.2, 0.4 * span):.1f}s into x{rate:g}",
                          file=sys.stderr, flush=True)
                t0 = time.perf_counter()
                results = asyncio.run(_drive(
                    addr, arrivals, rate=rate, vocab=vocab,
                    seed=args.seed, timeout=args.timeout,
                    max_seq_len=args.max_seq_len, pin_streams=True,
                    record=True, retries=retries))
                wall = time.perf_counter() - t0
                for r in results:
                    if r.get("ok") and "trace_id" in r:
                        phase_streams[r["trace_id"]] = {
                            "tokens": r.pop("tokens"),
                            "logprobs": r.pop("logprobs"),
                        }
                summary = _rate_summary(rate, arrivals, results, wall)
                summary["chaos"] = bool(chaos_here)
                curve.append(summary)
                lat = summary["latency_s"] or {}
                print(f"[{mode}] rate x{rate:g}: "
                      f"ok={summary['ok']}/{summary['n']} "
                      f"p50={lat.get('p50')} p99={lat.get('p99')}",
                      file=sys.stderr, flush=True)
            router_snap = _router_snap(addr)

            events_path = ""
            slo_report: Dict[str, Any] = {}
            if args.telemetry_dir:
                events_path = os.path.join(
                    args.telemetry_dir, f"events_{mode}.jsonl")
                telemetry.EVENTS.dump_jsonl(events_path)
                slo_report = slo_mod.build_report(
                    events_path, run_id=f"replay-{mode}",
                    source_name=events_path)
            telemetry.set_enabled(False)
            telemetry.EVENTS.clear()
            phases[mode] = {
                "curve": curve,
                "router": {k: router_snap.get(k) for k in
                           ("handoffs", "handoff_fallbacks", "roles",
                            "failovers")},
                "events_jsonl": events_path,
                "slo": {k: slo_report.get(k) for k in
                        ("inter_token_s", "ttft_s", "e2e_s",
                         "handoff", "trajectories")} if slo_report else {},
            }
            streams[mode] = phase_streams
            if slo_report and mode == "disagg" and args.slo_report:
                with open(args.slo_report, "w") as f:
                    json.dump(slo_report, f, indent=2)
                    f.write("\n")
                md = os.path.splitext(args.slo_report)[0] + ".md"
                with open(md, "w") as f:
                    f.write(slo_mod.render_markdown(slo_report))
        finally:
            if chaos_timer is not None:
                chaos_timer.cancel()
            for stop in reversed(stops):
                try:
                    stop()
                except Exception as e:  # noqa: BLE001 — teardown only
                    print(f"teardown: {str(e)[:120]}", file=sys.stderr)

    # exactness: same trace_id => same pinned stream => identical tokens
    # regardless of fleet topology (counter-keyed sampler; logprob
    # mismatches are reported but informational — decode-vs-suffix XLA
    # programs may differ in the last ulp at the handoff boundary)
    common = sorted(set(streams["colocated"]) & set(streams["disagg"]))
    token_mism = [t for t in common
                  if streams["colocated"][t]["tokens"]
                  != streams["disagg"][t]["tokens"]]
    lp_mism = [t for t in common
               if streams["colocated"][t]["logprobs"]
               != streams["disagg"][t]["logprobs"]]
    bit_identity = {
        "compared": len(common),
        "token_mismatches": len(token_mism),
        "token_mismatch_ids": token_mism[:8],
        "logprob_mismatches": len(lp_mism),
    }

    def _it_p99(mode: str) -> Optional[float]:
        d = (phases[mode]["slo"] or {}).get("inter_token_s") or {}
        return d.get("p99")

    co_p99, dis_p99 = _it_p99("colocated"), _it_p99("disagg")
    interference = {
        "colocated_inter_token_p99": co_p99,
        "disagg_inter_token_p99": dis_p99,
        "win": (co_p99 is not None and dis_p99 is not None
                and dis_p99 <= co_p99),
    }
    disagg_errors = sum(s["errors"] for s in phases["disagg"]["curve"])
    gates = {
        "bit_identity": len(common) > 0 and not token_mism,
        "handoffs_nonzero":
            int(phases["disagg"]["router"].get("handoffs") or 0) > 0,
        "zero_lost": disagg_errors == 0,
    }

    out: Dict[str, Any] = {
        "schema": SCHEMA,
        "mode": "disagg_ab",
        "source": source,
        "fleet": {"model": args.model, "servers": n_servers,
                  "n_slots": args.n_slots,
                  "max_seq_len": args.max_seq_len,
                  "chaos": bool(args.chaos),
                  "device_kind": jax.devices()[0].device_kind},
        "workload": wl.summarize(arrivals),
        "phases": phases,
        "bit_identity": bit_identity,
        "interference": interference,
        "gates": gates,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    print(json.dumps(out))
    failed = [k for k, v in gates.items() if not v]
    if failed:
        print(f"FAIL: disagg gates violated: {failed}", file=sys.stderr)
        return 1
    print(f"disagg A/B ok: {bit_identity['compared']} streams "
          f"bit-identical, handoffs="
          f"{phases['disagg']['router'].get('handoffs')}, "
          f"inter-token p99 {dis_p99} vs colocated {co_p99}",
          file=sys.stderr)
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny",
                   help="serving_model_setup model (tiny = CPU smoke)")
    p.add_argument("--servers", type=int, default=1,
                   help="self-hosted GenServer count (ignored with --addr)")
    p.add_argument("--router", action="store_true",
                   help="front the servers with the real Router (forced "
                        "on when --servers > 1)")
    p.add_argument("--addr", default="",
                   help="target an existing fleet instead of self-hosting")
    p.add_argument("--trace", default="",
                   help="events.jsonl to replay (arrival clock + shapes)")
    p.add_argument("--workload", default="mixed", choices=["mixed"],
                   help="synthetic workload when no --trace is given")
    p.add_argument("--duration", type=float, default=8.0,
                   help="synthetic workload span at 1x, seconds")
    p.add_argument("--base-rps", type=float, default=2.0,
                   help="synthetic workload request rate at 1x")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--rates", default="1,4,16",
                   help="comma-separated arrival-rate multipliers (1-100x)")
    p.add_argument("--n-slots", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=256)
    p.add_argument("--host-offload", action="store_true",
                   help="self-hosted servers spill evicted prefixes to a "
                        "host-DRAM LRU tier (ISSUE 16)")
    p.add_argument("--host-cache-mb", type=int, default=64,
                   help="host overflow tier capacity per server, MiB")
    p.add_argument("--host-min-tokens", type=int, default=32,
                   help="minimum retained length worth spilling to host")
    p.add_argument("--max-new-tokens", type=int, default=16,
                   help="synthetic workload decode-budget ceiling")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated A/B (ISSUE 17): colocated control "
                        "vs 1-prefill + N-1-decode fleet over the same "
                        "workload, gated on stream bit-identity")
    p.add_argument("--chaos", action="store_true",
                   help="with --disagg: kill the prefill server mid-way "
                        "through the last rate; zero lost trajectories "
                        "required (driver retries emulate client failover)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the pre-measurement compile warmup")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-request client timeout (timeouts count as "
                        "errors, i.e. lost trajectories)")
    p.add_argument("--out", default="", help="curve JSON path")
    p.add_argument("--telemetry-dir", default="",
                   help="enable telemetry and dump events.jsonl here")
    p.add_argument("--slo-report", default="",
                   help="also build an SLO report JSON from the run's "
                        "events (markdown twin next to it)")
    args = p.parse_args()

    rates = sorted({float(r) for r in args.rates.split(",") if r})
    if not rates:
        p.error("--rates must name at least one multiplier")
    if any(r <= 0 or r > 100 for r in rates):
        p.error("--rates multipliers must be in (0, 100]")

    if args.telemetry_dir:
        os.makedirs(args.telemetry_dir, exist_ok=True)

    # workload ---------------------------------------------------------
    if args.trace:
        arrivals = wl.arrivals_from_trace(
            args.trace, default_budget=args.max_new_tokens)
        if not arrivals:
            p.error(f"--trace {args.trace} has no rollout_submit events")
        source = {"trace": args.trace}
    else:
        arrivals = wl.synthetic_mixed(
            seed=args.seed, duration_s=args.duration,
            base_rps=args.base_rps,
            max_prompt_len=max(16, args.max_seq_len // 2),
            max_new_tokens=args.max_new_tokens)
        source = {"synthetic": args.workload, "seed": args.seed,
                  "duration_s": args.duration, "base_rps": args.base_rps}
    print(f"workload: {wl.summarize(arrivals)}", file=sys.stderr, flush=True)

    if args.chaos and not args.disagg:
        p.error("--chaos requires --disagg")
    if args.disagg:
        if args.addr:
            p.error("--disagg self-hosts both fleets; drop --addr")
        return _run_ab(args, p, arrivals, rates, source)

    # fleet ------------------------------------------------------------
    stops = []
    fleet: Dict[str, Any] = {"external": bool(args.addr)}
    vocab = 512
    warm_addrs: List[str]
    if args.addr:
        addr = args.addr
        warm_addrs = [addr]
        _wait_health(addr)
    else:
        import jax

        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        import bench_serving as bs

        cfg, params = bs.serving_model_setup(args.model)
        vocab = cfg.vocab_size
        server_addrs = []
        for _ in range(args.servers):
            a, stop = _boot_server(cfg, params, args)
            server_addrs.append(a)
            stops.append(stop)
        addr = server_addrs[0]
        warm_addrs = server_addrs
        use_router = args.router or args.servers > 1
        if use_router:
            addr, stop = _boot_router(server_addrs)
            stops.append(stop)
        fleet.update(model=args.model, servers=args.servers,
                     router=use_router, n_slots=args.n_slots,
                     max_seq_len=args.max_seq_len,
                     device_kind=jax.devices()[0].device_kind)
        print(f"fleet up: {server_addrs} -> {addr}",
              file=sys.stderr, flush=True)

    # replay -----------------------------------------------------------
    curve = []
    run_prefix_cache: Optional[Dict[str, Any]] = None
    try:
        if not args.no_warmup:
            tw = time.perf_counter()
            asyncio.run(_warmup(warm_addrs, vocab=vocab,
                                max_seq_len=args.max_seq_len))
            print(f"warmup done in {time.perf_counter() - tw:.1f}s",
                  file=sys.stderr, flush=True)
        # telemetry goes live only now: warmup/compile spans are not SLO
        # evidence, and a half-recorded warmup trace would fail the
        # completeness linter
        if args.telemetry_dir:
            telemetry.set_enabled(True)
        run_cache_before = _scrape_prefix_stats(warm_addrs)
        for rate in rates:
            cache_before = _scrape_prefix_stats(warm_addrs)
            t0 = time.perf_counter()
            results = asyncio.run(_drive(
                addr, arrivals, rate=rate, vocab=vocab, seed=args.seed,
                timeout=args.timeout, max_seq_len=args.max_seq_len))
            wall = time.perf_counter() - t0
            summary = _rate_summary(rate, arrivals, results, wall)
            # hit-rate-vs-latency: every point on the latency curve
            # carries the prefix-cache composition that produced it
            summary["prefix_cache"] = _prefix_cache_delta(
                cache_before, _scrape_prefix_stats(warm_addrs))
            curve.append(summary)
            lat = summary["latency_s"] or {}
            print(f"rate x{rate:g}: ok={summary['ok']}/{summary['n']} "
                  f"p50={lat.get('p50')} p99={lat.get('p99')} "
                  f"tok/s={summary['output_tokens_per_s']} "
                  f"hit_rate={summary['prefix_cache']['hit_rate']}",
                  file=sys.stderr, flush=True)
        run_prefix_cache = _prefix_cache_delta(
            run_cache_before, _scrape_prefix_stats(warm_addrs))
    finally:
        for stop in reversed(stops):
            try:
                stop()
            except Exception as e:  # noqa: BLE001 — teardown only
                print(f"teardown: {str(e)[:120]}", file=sys.stderr)

    out: Dict[str, Any] = {
        "schema": SCHEMA,
        "source": source,
        "fleet": fleet,
        "workload": wl.summarize(arrivals),
        "rates": curve,
        "prefix_cache": run_prefix_cache,
    }

    if args.telemetry_dir:
        events_path = os.path.join(args.telemetry_dir, "events.jsonl")
        n_events = telemetry.EVENTS.dump_jsonl(events_path)
        out["telemetry"] = {
            "events_jsonl": events_path,
            "n_events": n_events,
            "dropped_events": telemetry.EVENTS.dropped,
        }
        if args.slo_report:
            report = slo_mod.build_report(
                events_path, run_id="replay",
                source_name=events_path)
            # the prefix-cache composition rides the SLO report so
            # check_slo.py can band the global hit rate alongside the
            # latency percentiles (baseline key: prefix_cache.hit_rate)
            if run_prefix_cache is not None:
                report["prefix_cache"] = run_prefix_cache
            with open(args.slo_report, "w") as f:
                json.dump(report, f, indent=2)
                f.write("\n")
            md_path = os.path.splitext(args.slo_report)[0] + ".md"
            with open(md_path, "w") as f:
                f.write(slo_mod.render_markdown(report))
            out["slo_report"] = args.slo_report
    elif args.slo_report:
        p.error("--slo-report requires --telemetry-dir (events feed it)")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
