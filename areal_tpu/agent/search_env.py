"""Local-corpus search environment for search-agent RL.

Capability counterpart of the reference's search-agent example
(examples/search-agent, which drives a retrieval service): a `search` tool
over an in-memory corpus (BM25-lite scoring — no external service, fits
the no-egress test environment) plus the standard `verify_answer` tool.
Episodes reward answers whose ground truth matches after retrieval.
"""

import math
import re
from collections import Counter
from typing import Any, Dict, List, Sequence, Tuple

from areal_tpu.api.env import Environment
from areal_tpu.reward.math_parser import extract_answer

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokens(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


class SearchIndex:
    """BM25-lite index over a passage corpus.

    Built once and shared across episodes (datasets attach one index per
    shared corpus — building tf/df tables per episode would pay O(corpus)
    on the rollout event loop for every sample)."""

    def __init__(self, corpus: Sequence[str], k1: float = 1.5, b: float = 0.75):
        self.corpus = list(corpus)
        self._docs = [_tokens(d) for d in self.corpus]
        self._tfs = [Counter(toks) for toks in self._docs]
        self._df: Counter = Counter()
        for toks in self._docs:
            self._df.update(set(toks))
        self._avg_len = (
            sum(len(t) for t in self._docs) / max(1, len(self._docs))
        )
        self.k1 = k1
        self.b = b

    def _score(self, query_toks: List[str], doc_idx: int) -> float:
        tf = self._tfs[doc_idx]
        doc_len = len(self._docs[doc_idx])
        N = len(self._docs)
        score = 0.0
        for q in query_toks:
            if q not in tf:
                continue
            idf = math.log(1 + (N - self._df[q] + 0.5) / (self._df[q] + 0.5))
            denom = tf[q] + self.k1 * (
                1 - self.b + self.b * doc_len / max(self._avg_len, 1e-8)
            )
            score += idf * tf[q] * (self.k1 + 1) / denom
        return score

    def search(self, query: str, k: int = 3) -> List[str]:
        q = _tokens(query)
        scores = [self._score(q, i) for i in range(len(self.corpus))]
        ranked = sorted(range(len(scores)), key=scores.__getitem__, reverse=True)
        return [self.corpus[i] for i in ranked[:k] if scores[i] > 0]


class LocalSearchEnv(Environment):
    """`search(query, k)` returns the top-k corpus passages by a BM25-style
    score; `verify_answer(completion)` grades the final answer."""

    def __init__(
        self,
        corpus: Sequence[str],
        answer: str,
        k1: float = 1.5,
        b: float = 0.75,
        index: "SearchIndex" = None,
    ):
        self.index = index if index is not None else SearchIndex(corpus, k1, b)
        self.corpus = self.index.corpus
        self.answer = str(answer)
        self.n_searches = 0

    # ------------------------------------------------------------------

    def search(self, query: str, k: int = 3) -> List[str]:
        self.n_searches += 1
        return self.index.search(query, k)

    # ------------------------------------------------------------------

    def list_tools(self) -> List[Dict[str, Any]]:
        return [
            {
                "name": "search",
                "description": "Retrieve top-k passages for a query.",
                "parameters": {
                    "type": "object",
                    "properties": {
                        "query": {"type": "string"},
                        "k": {"type": "integer", "default": 3},
                    },
                    "required": ["query"],
                },
            },
            {
                "name": "verify_answer",
                "description": "Check a final answer against the ground truth.",
                "parameters": {
                    "type": "object",
                    "properties": {"completion": {"type": "string"}},
                    "required": ["completion"],
                },
            },
        ]

    async def aexecute_tool(
        self, tool_name: str, arguments: Dict[str, Any]
    ) -> Tuple[Any, float, bool]:
        if tool_name == "search":
            hits = self.search(
                arguments["query"], int(arguments.get("k", 3))
            )
            return hits, 0.0, False  # episode continues
        if tool_name == "verify_answer":
            # the answer must be COMMITTED (\boxed / extractable), not merely
            # present somewhere — echoing a retrieved passage scores 0, or a
            # paste-the-observations policy farms the reward
            pred = extract_answer(arguments["completion"])
            ok = (
                pred is not None
                and pred.strip().lower() == self.answer.strip().lower()
            )
            # done only on success (MathVerifyEnv convention) so multi-turn
            # agents can retry
            return None, float(ok), ok
        raise ValueError(f"unknown tool {tool_name!r}")
