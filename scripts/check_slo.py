"""SLO regression gate: diff a report against a checked-in baseline.

Every perf/fleet PR gets judged by the SLO reports the trace-analytics
layer produces (`python -m areal_tpu.obs.slo`, `scripts/bench_replay.py
--slo-report`).  This gate compares one report against
`tests/data/slo_baseline.json` with per-metric tolerance bands:

- **completeness is non-negotiable**: a report whose trace log dropped
  events, has orphan spans, or violates the accounting identity hard-
  fails regardless of tolerances — numbers from a lossy log are not
  evidence;
- **soft band**: each baseline metric carries a relative tolerance
  (rig noise on shared CI runners is real; bands are wide on purpose);
- **hard band**: ``hard_fail_ratio`` (default 2.0) — a >2x regression
  fails even in ``--hard-only`` mode, which is what CI runs so a noisy
  runner can't block a PR but a real regression still does.

Baseline format (per metric, dotted path into the report JSON):

  {"schema": "areal-slo-baseline/v1",
   "hard_fail_ratio": 2.0,
   "metrics": {
     "e2e_s.p99":   {"baseline": 1.9, "tolerance": 0.75,
                     "direction": "upper"},
     "goodput.output_tokens_per_s": {"baseline": 140.0,
                     "tolerance": 0.5, "direction": "lower"}}}

``direction: upper`` fails when the report exceeds
``baseline * (1 + tolerance)`` (latency-like); ``lower`` fails when it
drops below ``baseline * (1 - tolerance)`` (throughput-like).

``--write-baseline`` regenerates the baseline from a known-good report
(keeping the metric list and bands), so updating it after an accepted
perf change is one command, not hand-editing JSON.

Exit codes: 0 = within bands; 1 = any violation (soft violations are
ignored under ``--hard-only``); 2 = unusable input.
"""

import argparse
import json
import sys
from typing import Any, Dict, Optional, Tuple

SCHEMA = "areal-slo-baseline/v1"


def lookup(report: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = report
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def check_metric(name: str, spec: Dict[str, Any], value: Optional[float],
                 hard_ratio: float) -> Tuple[str, str]:
    """-> (verdict, detail); verdict in {ok, soft, hard, missing}."""
    base = float(spec["baseline"])
    tol = float(spec.get("tolerance", 0.5))
    direction = spec.get("direction", "upper")
    if value is None:
        return "missing", f"{name}: not present in report"
    # hard must never be easier to trip than soft (a wide soft band with
    # a small hard ratio would otherwise invert the two)
    if direction == "upper":
        soft_limit = base * (1.0 + tol)
        hard_limit = max(base * hard_ratio, soft_limit)
        if value > hard_limit:
            return "hard", (f"{name}: {value:.4g} > {hard_limit:.4g} "
                            f"(baseline {base:.4g} x{hard_ratio:g})")
        if value > soft_limit:
            return "soft", (f"{name}: {value:.4g} > {soft_limit:.4g} "
                            f"(baseline {base:.4g} +{tol:.0%})")
    elif direction == "lower":
        soft_limit = base * (1.0 - tol)
        hard_limit = min(base / hard_ratio, soft_limit)
        if value < hard_limit:
            return "hard", (f"{name}: {value:.4g} < {hard_limit:.4g} "
                            f"(baseline {base:.4g} /{hard_ratio:g})")
        if value < soft_limit:
            return "soft", (f"{name}: {value:.4g} < {soft_limit:.4g} "
                            f"(baseline {base:.4g} -{tol:.0%})")
    else:
        return "missing", f"{name}: unknown direction {direction!r}"
    return "ok", f"{name}: {value:.4g} (baseline {base:.4g})"


def run_gate(report: Dict[str, Any], baseline: Dict[str, Any],
             hard_only: bool = False) -> Tuple[int, str]:
    lines = []
    hard = soft = 0

    # completeness + accounting identity gate first: tolerances cannot
    # excuse numbers computed from a lossy or inconsistent trace log
    comp = report.get("completeness", {})
    acct = report.get("accounting", {})
    if not comp.get("complete", False):
        hard += 1
        lines.append(
            "HARD completeness: dropped_events="
            f"{comp.get('dropped_events')} orphans="
            f"{len(comp.get('orphan_traces', []))} unjoined_resubmits="
            f"{comp.get('unjoined_resubmits')}")
    if not acct.get("ok", False):
        hard += 1
        lines.append(
            f"HARD accounting identity: violations={acct.get('violations')} "
            f"max_rel_err={acct.get('max_rel_err')}")

    hard_ratio = float(baseline.get("hard_fail_ratio", 2.0))
    for name, spec in sorted(baseline.get("metrics", {}).items()):
        verdict, detail = check_metric(
            name, spec, lookup(report, name), hard_ratio)
        if verdict == "hard":
            hard += 1
            lines.append(f"HARD {detail}")
        elif verdict in ("soft", "missing"):
            soft += 1
            lines.append(f"soft {detail}")
        else:
            lines.append(f"  ok {detail}")

    failed = hard > 0 or (soft > 0 and not hard_only)
    verdict = "FAIL" if failed else "PASS"
    mode = " (hard-only)" if hard_only else ""
    lines.append(f"{verdict}{mode}: {hard} hard, {soft} soft violations "
                 f"over {len(baseline.get('metrics', {}))} metrics")
    return (1 if failed else 0), "\n".join(lines)


def write_baseline(report: Dict[str, Any], old: Optional[Dict[str, Any]],
                   tolerance: float) -> Dict[str, Any]:
    """New baseline from a known-good report: keep the old metric list
    and bands when present, refresh only the values; otherwise seed the
    default metric set."""
    if old and old.get("metrics"):
        metrics = {
            name: {**spec, "baseline": lookup(report, name)}
            for name, spec in old["metrics"].items()
            if lookup(report, name) is not None
        }
        hard_ratio = float(old.get("hard_fail_ratio", 2.0))
    else:
        defaults = [
            ("e2e_s.p50", "upper"),
            ("e2e_s.p99", "upper"),
            ("ttft_s.p99", "upper"),
            ("stages.admission_wait.p99", "upper"),
            ("stages.decode.p99", "upper"),
            ("goodput.output_tokens_per_s", "lower"),
        ]
        metrics = {}
        for name, direction in defaults:
            v = lookup(report, name)
            if v is not None:
                metrics[name] = {"baseline": v, "tolerance": tolerance,
                                 "direction": direction}
        hard_ratio = 2.0
    return {
        "schema": SCHEMA,
        "source_report": report.get("run_id", ""),
        "hard_fail_ratio": hard_ratio,
        "metrics": metrics,
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--report", required=True, help="SLO report JSON")
    p.add_argument("--baseline", default="tests/data/slo_baseline.json")
    p.add_argument("--hard-only", action="store_true",
                   help="CI mode: only completeness violations and "
                        ">hard_fail_ratio regressions fail")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate --baseline from --report instead of "
                        "gating (run after an accepted perf change)")
    p.add_argument("--tolerance", type=float, default=0.75,
                   help="default soft band when seeding a new baseline")
    args = p.parse_args()

    try:
        with open(args.report) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        print(f"unusable report {args.report}: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        old = None
        try:
            with open(args.baseline) as f:
                old = json.load(f)
        except (OSError, ValueError):
            pass
        baseline = write_baseline(report, old, args.tolerance)
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2)
            f.write("\n")
        print(f"baseline written: {args.baseline} "
              f"({len(baseline['metrics'])} metrics)")
        return 0

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"unusable baseline {args.baseline}: {e}", file=sys.stderr)
        return 2
    if baseline.get("schema") != SCHEMA:
        print(f"baseline schema mismatch: {baseline.get('schema')!r}",
              file=sys.stderr)
        return 2

    rc, text = run_gate(report, baseline, hard_only=args.hard_only)
    print(text)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
