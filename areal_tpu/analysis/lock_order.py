"""C5 — interprocedural lock-order / deadlock discipline.

Three rules over the callgraph.py substrate, all aimed at the failure
class PR 3's per-file C1 cannot see — a lock taken *here* interacting
with something that happens *there*:

- ``lock-order``: a cyclic acquisition order between registered locks.
  Nesting facts are collected lexically (``with self._a:`` containing
  ``with self._b:``) AND through calls (holding ``_a`` while calling a
  function whose transitive acquisition set contains ``_b``), then
  combined with the **declared** order edges (``# lock-order: _a -> _b``
  comments in a class body — the sanctioned nesting).  Any discovered
  edge participating in a cycle of the combined digraph is reported at
  its acquisition/call site.  Re-acquiring a held non-reentrant lock
  (lexically or via a callee) is the degenerate one-lock cycle and is
  reported under the same rule — for ``asyncio.Lock`` lexical nesting is
  a guaranteed same-task deadlock.
- ``blocking-under-lock``: an ``await``, a known blocking call (the C3
  tables: ``time.sleep``, ``requests.*``, subprocess waits, file I/O), or
  a user-callback invocation (``*.finish(...)`` — it runs arbitrary
  ``on_done`` hooks that may re-enter the engine) while a
  ``threading``-kind lock is held, directly or through any callee chain.
  Holding an ``asyncio.Lock`` across ``await`` is legal and not flagged.
- ``atomicity-split``: within one function, a ``_GUARDED_FIELDS`` field
  read in one critical section and then **blindly overwritten** in a
  later critical section of the same lock — the classic check-then-act
  race (ADVICE r5's ``_holdback`` bug shape).  A write whose value
  expression itself re-reads the field (merge/read-modify-write, e.g.
  ``self._holdback = leftover + self._holdback``) re-validates under the
  second lock hold and is exempt, as are ``+=``-style AugAssigns.

Lock identity is (owning class, attribute): ``Router._lock`` and
``GenEngine._lock`` are distinct nodes, so cross-class edges only arise
through actual resolved calls.
"""

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from areal_tpu.analysis.async_blocking import (
    _BLOCKING_EXACT,
    _BLOCKING_METHODS,
    _BLOCKING_PREFIXES,
)
from areal_tpu.analysis.callgraph import CallGraph, FuncInfo, dotted_name
from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression
from areal_tpu.analysis.lock_discipline import _guarded_fields, _holds_of

RULE_ORDER = "lock-order"
RULE_BLOCK = "blocking-under-lock"
RULE_ATOMIC = "atomicity-split"

# invoking these methods runs user-supplied callbacks (GenRequest.finish
# fires on_done hooks and wakes waiters) — arbitrary re-entrant code
_CALLBACK_METHODS = {"finish"}

_ORDER_DECL_RE = re.compile(
    r"#\s*lock-order:\s*([A-Za-z_]\w*)\s*->\s*([A-Za-z_]\w*)"
)

LockId = Tuple[str, str]  # (owning class key, lock attribute)


def _fmt(lock: LockId) -> str:
    cls = lock[0].split("::")[-1]
    return f"{cls}.{lock[1]}"


@dataclass
class _Event:
    kind: str  # "acquire" | "call" | "await" | "blocking"
    line: int
    held: FrozenSet[LockId]
    lock: Optional[LockId] = None  # acquire
    callee: Optional[str] = None  # call
    detail: str = ""  # blocking description / call text


@dataclass
class _Summary:
    fi: FuncInfo
    entry_held: Set[LockId] = field(default_factory=set)
    acquires: Set[LockId] = field(default_factory=set)
    events: List[_Event] = field(default_factory=list)
    blocks: Optional[Tuple[int, str]] = None  # first local witness


class _Walker(ast.NodeVisitor):
    """Lexical walk of one function body tracking the held lock set.
    Nested defs/lambdas are skipped: they run at an unknown time, so an
    enclosing `with` guarantees nothing about their execution context."""

    def __init__(self, graph: CallGraph, summary: _Summary):
        self.graph = graph
        self.s = summary
        self.held: Set[LockId] = set(summary.entry_held)

    # -- nested contexts are opaque to C5 -------------------------------
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        pass

    def visit_Lambda(self, node):  # noqa: N802
        pass

    # -- with blocks ----------------------------------------------------
    def visit_With(self, node: ast.With):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._visit_with(node, is_async=True)

    def _visit_with(self, node, is_async: bool):
        added: List[LockId] = []
        for item in node.items:
            e = item.context_expr
            self.visit(e)
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
            ):
                resolved = self.graph.lock_of(self.s.fi, e.attr)
                if resolved is None:
                    continue
                ckey, li = resolved
                lock: LockId = (ckey, li.name)
                self.s.events.append(
                    _Event(
                        "acquire",
                        e.lineno,
                        frozenset(self.held),
                        lock=lock,
                        detail=li.kind,
                    )
                )
                self.s.acquires.add(lock)
                if lock not in self.held:
                    self.held.add(lock)
                    added.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in added:
            self.held.discard(lock)

    # -- blocking primitives --------------------------------------------
    def visit_Await(self, node: ast.Await):
        self.s.events.append(
            _Event("await", node.lineno, frozenset(self.held))
        )
        if self.s.blocks is None:
            self.s.blocks = (node.lineno, "await")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        d = dotted_name(node.func)
        blocking: Optional[str] = None
        if d is not None:
            if d in _BLOCKING_EXACT:
                blocking = f"{d}()"
            elif any(d.startswith(p) for p in _BLOCKING_PREFIXES):
                blocking = f"{d}()"
        if (
            blocking is None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_METHODS
        ):
            blocking = f".{node.func.attr}()"
        if (
            blocking is None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CALLBACK_METHODS
        ):
            blocking = (
                f".{node.func.attr}() (user callback / waiter wakeup)"
            )
        if blocking is not None:
            self.s.events.append(
                _Event(
                    "blocking",
                    node.lineno,
                    frozenset(self.held),
                    detail=blocking,
                )
            )
            if self.s.blocks is None:
                self.s.blocks = (node.lineno, blocking)
        callee = None
        for call, key in self.graph.calls.get(self.s.fi.key, ()):
            if call is node:
                callee = key
                break
        if callee is not None:
            self.s.events.append(
                _Event(
                    "call",
                    node.lineno,
                    frozenset(self.held),
                    callee=callee,
                    detail=d or "",
                )
            )
        self.generic_visit(node)


def _declared_edges(
    graph: CallGraph,
) -> Tuple[Set[Tuple[LockId, LockId]], Dict[Tuple[LockId, LockId], int]]:
    """`# lock-order: _a -> _b` comments inside a class body declare the
    sanctioned nesting for that class's locks."""
    edges: Set[Tuple[LockId, LockId]] = set()
    lines: Dict[Tuple[LockId, LockId], int] = {}
    for ci in graph.classes.values():
        end = max(
            (getattr(n, "end_lineno", ci.node.lineno) or ci.node.lineno)
            for n in ast.walk(ci.node)
        )
        for ln in range(ci.node.lineno, end + 1):
            m = _ORDER_DECL_RE.search(ci.sf.comments.get(ln, ""))
            if not m:
                continue
            a, b = m.group(1), m.group(2)
            if a in ci.locks and b in ci.locks:
                edge = ((ci.key, a), (ci.key, b))
                edges.add(edge)
                lines[edge] = ln
    return edges, lines


def _cycle_nodes(adj: Dict[LockId, Set[LockId]]) -> Set[LockId]:
    """Nodes on any directed cycle (Tarjan SCCs of size > 1, plus
    self-loops)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    onstack: Set[LockId] = set()
    stack: List[LockId] = []
    out: Set[LockId] = set()
    counter = [0]

    def strongconnect(v: LockId):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                scc.append(w)
                if w == v:
                    break
            if len(scc) > 1 or v in adj.get(v, ()):
                out.update(scc)

    nodes = set(adj)
    for tos in adj.values():
        nodes |= tos
    for v in nodes:
        if v not in index:
            strongconnect(v)
    return out


def check_lock_order(files: Dict[str, SourceFile]) -> List[Finding]:
    graph = CallGraph(files)
    findings: List[Finding] = []

    # ---- per-function local summaries ---------------------------------
    summaries: Dict[str, _Summary] = {}
    for key, fi in graph.functions.items():
        if fi.name == "__init__":
            continue
        s = _Summary(fi)
        if fi.cls_key is not None:
            ci = graph.classes[fi.cls_key]
            for lock_name in _holds_of(fi.sf, fi.node):
                if lock_name in ci.locks:
                    s.entry_held.add((fi.cls_key, lock_name))
        w = _Walker(graph, s)
        for stmt in fi.node.body:
            w.visit(stmt)
        summaries[key] = s

    # ---- fixpoint: transitive acquires + blocking witnesses -----------
    edges = {
        key: [
            e.callee
            for e in s.events
            if e.kind == "call" and e.callee in summaries
        ]
        for key, s in summaries.items()
    }
    from areal_tpu.analysis.callgraph import fixpoint

    trans_acq = fixpoint(
        {key: set(s.acquires) for key, s in summaries.items()}, edges
    )
    trans_block = fixpoint(
        {
            key: ({s.blocks[1]} if s.blocks is not None else set())
            for key, s in summaries.items()
        },
        edges,
    )

    # ---- walk events: re-entry, await/blocking-under-lock, edges ------
    lock_info = {
        (ckey, name): li
        for ckey, ci in graph.classes.items()
        for name, li in ci.locks.items()
    }

    def thread_held(held: FrozenSet[LockId]) -> List[LockId]:
        # unknown-kind locks are NOT treated as threading: flagging them
        # would fire on asyncio locks behind aliased imports
        return [l for l in held if lock_info[l].kind == "threading"]

    order_edges: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    for key, s in summaries.items():
        sf = s.fi.sf
        for e in s.events:
            if e.kind == "acquire":
                assert e.lock is not None
                li = lock_info[e.lock]
                if e.lock in e.held and not li.reentrant and li.kind in (
                    "threading",
                    "asyncio",
                ):
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                RULE_ORDER,
                                sf.rel,
                                e.line,
                                f"{s.fi.name} re-acquires non-reentrant "
                                f"self.{e.lock[1]} already held on this "
                                f"path — guaranteed self-deadlock",
                            ),
                        )
                    )
                for h in e.held:
                    if h != e.lock:
                        order_edges.setdefault(
                            (h, e.lock), (sf.rel, e.line)
                        )
            elif e.kind == "await":
                for h in thread_held(e.held):
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                RULE_BLOCK,
                                sf.rel,
                                e.line,
                                f"await while holding threading lock "
                                f"{_fmt(h)} — stalls every other thread "
                                f"contending for it",
                            ),
                        )
                    )
            elif e.kind == "blocking":
                for h in thread_held(e.held):
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                RULE_BLOCK,
                                sf.rel,
                                e.line,
                                f"{e.detail} called while holding "
                                f"{_fmt(h)} — move it outside the "
                                f"critical section (collect-then-call)",
                            ),
                        )
                    )
            elif e.kind == "call" and e.callee in summaries:
                callee_acq = trans_acq.get(e.callee, set())
                for h in e.held:
                    li = lock_info[h]
                    if h in callee_acq and not li.reentrant:
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    RULE_ORDER,
                                    sf.rel,
                                    e.line,
                                    f"{s.fi.name} holds {_fmt(h)} and "
                                    f"calls {e.callee.split('::')[-1]} "
                                    f"which (transitively) re-acquires "
                                    f"it — self-deadlock on a "
                                    f"non-reentrant lock",
                                ),
                            )
                        )
                    for b in callee_acq:
                        if b != h:
                            order_edges.setdefault(
                                (h, b), (sf.rel, e.line)
                            )
                if thread_held(e.held) and trans_block.get(e.callee):
                    witness = sorted(trans_block[e.callee])[0]
                    for h in thread_held(e.held):
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    RULE_BLOCK,
                                    sf.rel,
                                    e.line,
                                    f"call to "
                                    f"{e.callee.split('::')[-1]} may "
                                    f"block ({witness}) while holding "
                                    f"{_fmt(h)}",
                                ),
                            )
                        )

    # ---- cycle detection over discovered + declared edges -------------
    declared, _decl_lines = _declared_edges(graph)
    adj: Dict[LockId, Set[LockId]] = {}
    for (a, b) in set(order_edges) | declared:
        adj.setdefault(a, set()).add(b)
    cyclic = _cycle_nodes(adj)
    for (a, b), (rel, line) in sorted(order_edges.items(), key=lambda kv: kv[1]):
        if (a, b) in declared:
            continue  # sanctioned nesting
        if a in cyclic and b in cyclic and b in adj and a in _reachable(
            adj, b
        ):
            sf = files[rel]
            findings.append(
                apply_suppression(
                    sf,
                    Finding(
                        RULE_ORDER,
                        rel,
                        line,
                        f"acquiring {_fmt(b)} while holding {_fmt(a)} "
                        f"closes a lock-order cycle (declare the "
                        f"sanctioned order with `# lock-order: a -> b` "
                        f"or invert the nesting)",
                    ),
                )
            )

    # ---- atomicity splits (intraprocedural, per guarded class) --------
    for ci in graph.classes.values():
        scratch: List[Finding] = []  # guard-syntax dupes belong to C1
        guarded = _guarded_fields(ci.sf, ci.node, scratch)
        if not guarded:
            continue
        for meth in ci.node.body:
            if (
                not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                or meth.name == "__init__"
            ):
                continue
            findings.extend(_atomicity_splits(ci.sf, ci.name, meth, guarded))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _reachable(adj: Dict[LockId, Set[LockId]], src: LockId) -> Set[LockId]:
    seen: Set[LockId] = set()
    work = [src]
    while work:
        v = work.pop()
        for w in adj.get(v, ()):
            if w not in seen:
                seen.add(w)
                work.append(w)
    return seen


def _attr_loads(node: ast.AST, fld: str) -> bool:
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Attribute)
            and n.attr == fld
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
        ):
            return True
    return False


def _field_reads_writes(
    with_node: ast.AST, fld: str
) -> Tuple[List[int], List[Tuple[int, bool]]]:
    """(read lines, [(write line, is_blind)]) for self.<fld> inside one
    critical section.  A write is *blind* when its value expression never
    re-reads the field (and it is not an AugAssign).  Constant writes
    (``self._dirty = True``, ``self._cache = None``) are NOT blind: they
    are deliberate resets/invalidations whose meaning cannot depend on
    what happened between the holds — the lost-update hazard this rule
    targets needs a computed value."""
    reads: List[int] = []
    writes: List[Tuple[int, bool]] = []
    for n in ast.walk(with_node):
        if isinstance(n, ast.Assign):
            hit = False
            for tgt in n.targets:
                base = tgt
                if isinstance(base, ast.Subscript):
                    base = base.value
                if (
                    isinstance(base, ast.Attribute)
                    and base.attr == fld
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    hit = True
            if hit:
                blind = not isinstance(
                    n.value, ast.Constant
                ) and not _attr_loads(n.value, fld)
                writes.append((n.lineno, blind))
                continue
        if isinstance(n, ast.AugAssign):
            base = n.target
            if isinstance(base, ast.Subscript):
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and base.attr == fld
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                writes.append((n.lineno, False))  # RMW: never blind
                continue
        if (
            isinstance(n, ast.Attribute)
            and n.attr == fld
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
            and isinstance(n.ctx, ast.Load)
        ):
            reads.append(n.lineno)
    return reads, writes


def _atomicity_splits(
    sf: SourceFile,
    cls_name: str,
    meth: ast.AST,
    guarded: Dict[str, str],
) -> List[Finding]:
    findings: List[Finding] = []
    # critical sections of this method, in source order, keyed by lock
    sections: List[Tuple[str, ast.AST]] = []
    for n in ast.walk(meth):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                e = item.context_expr
                if (
                    isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"
                ):
                    sections.append((e.attr, n))
    sections.sort(key=lambda p: p[1].lineno)
    for fld, lock in guarded.items():
        cs = [(ln, node) for (ln, node) in sections if ln == lock]
        for i, (_, early) in enumerate(cs):
            reads, _ = _field_reads_writes(early, fld)
            if not reads:
                continue
            for _, late in cs[i + 1 :]:
                if late is early:
                    continue
                _, writes = _field_reads_writes(late, fld)
                for line, blind in writes:
                    if blind:
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    RULE_ATOMIC,
                                    sf.rel,
                                    line,
                                    f"{cls_name}.{fld} read under "
                                    f"self.{lock} at line {reads[0]} "
                                    f"but blindly overwritten in a "
                                    f"LATER critical section — the "
                                    f"state may have changed between "
                                    f"the two holds (merge with the "
                                    f"current value or fuse the "
                                    f"sections)",
                                ),
                            )
                        )
            break  # only the first reading section anchors the split
    return findings
