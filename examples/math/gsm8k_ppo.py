"""GSM8K PPO with a learned critic — actor + value model.

The critic-based variant of the canonical GRPO loop (gsm8k_grpo.py).
Behavioral counterpart of the reference's PPO-with-critic algorithm layer
(lite: areal/engine/ppo/critic.py driven the same way as the actor;
legacy: realhf ppo_math_exp actor/critic MFCs): per step the critic's
per-token values feed GAE (advantages for the actor, returns for the
critic), then both models update on the same rollout batch.

Differences from gsm8k_grpo.py kept deliberate and small:
- `PPOConfig` (GRPOConfig + a `critic:` section) configures a second
  train engine that shares the actor's backbone config plus a scalar
  value head (`engine/ppo/critic.py`).
- `use_decoupled_loss`/group advantage normalisation still apply — the
  decoupled objective is orthogonal to where the baseline comes from.
- Save/recover cover BOTH models: the critic checkpoints beside the actor
  (saver name="critic"; value-head weights ride along) and the recover
  handler dumps/restores it via `extra_engines` so a resumed run keeps its
  learned baseline.

Launch:  python examples/math/gsm8k_ppo.py --config examples/math/gsm8k_ppo.yaml
(or via the launcher, which also starts generation servers:
 python -m areal_tpu.launcher.local examples/math/gsm8k_ppo.py --config ...)
"""

import os
import sys

import numpy as np

from areal_tpu.api.config import PPOConfig, load_expr_config, to_dict
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo, WeightUpdateMeta
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.engine.ppo import JaxPPOActor, JaxPPOCritic
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.reward import gsm8k_reward_fn
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.recover import (
    RecoverHandler,
    check_if_recover,
    config_fingerprint,
)
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.shutdown import PreemptionGuard, preempt_exit
from areal_tpu.utils.stats_logger import StatsLogger
from areal_tpu.workflow.rlvr import RLVRWorkflow

logger = logging.getLogger("gsm8k_ppo")


def main(argv):
    config, _ = load_expr_config(argv, PPOConfig)
    seeding.set_random_seed(config.seed, "trainer")
    guard = PreemptionGuard().install()

    tokenizer = None
    if config.tokenizer_path:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(config.tokenizer_path)

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type,
        split="train",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )

    rollout = RemoteJaxEngine(config.rollout)
    rollout.initialize(train_data_parallel_size=1)

    actor = JaxPPOActor(config.actor)
    actor.create_process_group()
    actor.initialize(ft_spec=ft_spec)

    critic = JaxPPOCritic(config.critic)
    critic.create_process_group()
    critic.initialize(ft_spec=ft_spec)

    ref = None
    if config.actor.kl_ctl > 0 and config.ref is not None:
        from areal_tpu.engine.jax_train import JaxTrainEngine

        ref = JaxTrainEngine(config.ref)
        ref.create_process_group()
        ref.initialize(ft_spec=ft_spec)

    if config.weight_update_mode == "transfer":
        weight_meta = WeightUpdateMeta.from_transfer(
            config.experiment_name, config.trial_name,
            live_commit=config.weight_update_live_commit,
        )
    else:
        weight_meta = WeightUpdateMeta.from_disk(
            config.experiment_name, config.trial_name, config.cluster.fileroot
        )

    from areal_tpu.api.reward import prewarm_reward_pool

    prewarm_reward_pool()
    workflow = RLVRWorkflow(
        reward_fn=gsm8k_reward_fn,
        gconfig=config.gconfig,
        tokenizer=tokenizer,
        dump_dir=os.path.join(
            StatsLogger.get_log_path(config.stats_logger), "generated"
        ),
    )

    saver = Saver(config.saver, ft_spec)
    checkpointer = Saver(config.checkpointer, ft_spec, for_recover=True)
    stats_logger = StatsLogger(config.stats_logger)
    recover = RecoverHandler(
        config.recover, ft_spec, fingerprint=config_fingerprint(to_dict(config))
    )
    dump_kwargs = dict(
        saver=saver, stats_logger=stats_logger, dataloader=dataloader,
        tokenizer=tokenizer, extra_engines={"critic": critic},
        inference_engine=rollout,
    )

    start_step = 0
    if check_if_recover(config.recover, run_id=int(os.environ.get("AREAL_RUN_ID", 0))):
        info = recover.load(
            actor,
            saver=saver,
            stats_logger=stats_logger,
            dataloader=dataloader,
            inference_engine=rollout,
            weight_update_meta=weight_meta,
            extra_engines={"critic": critic},
        )
        if info is not None:
            start_step = info.recover_start.global_step

    if config.warm_pack_shapes:
        actor.warm_shapes([tuple(s) for s in config.warm_pack_shapes])

    total_steps = config.total_train_steps or ft_spec.total_train_steps
    steps_per_epoch = ft_spec.steps_per_epoch

    for global_step in range(start_step, total_steps):
        epoch = global_step // steps_per_epoch
        epoch_step = global_step % steps_per_epoch
        step_info = StepInfo(
            epoch=epoch, epoch_step=epoch_step, global_step=global_step,
            steps_per_epoch=steps_per_epoch,
        )

        with stats.record_timing("rollout"):
            if config.async_training:
                batch = rollout.prepare_batch(dataloader, workflow=workflow)
            else:
                batch = rollout.rollout_batch(
                    [train_dataset[i % len(train_dataset)]
                     for i in range(
                         global_step * config.train_dataset.batch_size,
                         (global_step + 1) * config.train_dataset.batch_size,
                     )],
                    workflow=workflow,
                )

        if config.actor.recompute_logprob:
            with stats.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.compute_logp(batch)

        # the critic's per-token values are the GAE baseline (the whole
        # point of PPO-with-critic vs GRPO's group-mean baseline)
        with stats.record_timing("compute_values"):
            batch["values"] = critic.compute_values(batch)

        if ref is not None:
            with stats.record_timing("ref_logp"):
                batch["ref_logp"] = ref.forward(batch)

        with stats.record_timing("compute_advantages"):
            actor.compute_advantages(batch)  # consumes values -> returns

        with stats.record_timing("ppo_update"):
            train_stats = actor.ppo_update(batch)
            actor.step_lr_scheduler()

        with stats.record_timing("critic_update"):
            # prefix so critic loss/grad_norm don't shadow the actor's in
            # the merged commit line
            critic_stats = [
                {f"critic/{k}": v for k, v in d.items()}
                for d in critic.ppo_update(batch)
            ]
            critic.step_lr_scheduler()

        with stats.record_timing("stage_weights"):
            actor.set_version(global_step + 1)
            actor.stage_weights(weight_meta)
        with stats.record_timing("update_weights"):
            # a live transfer commit swaps without aborting — the server
            # keeps decoding through the publish, so the client pipeline
            # need not pause; only the abort choreography drains in-flight
            live = (weight_meta.type == "transfer"
                    and weight_meta.live_commit)
            if not live:
                rollout.pause()
            actor.update_weights(weight_meta)
            rollout.update_weights(weight_meta)
            rollout.set_version(global_step + 1)
            if not live:
                rollout.resume()

        with stats.record_timing("save"):
            saved = saver.save(
                actor, epoch, epoch_step, global_step, tokenizer=tokenizer
            )
            if saved is not None:
                # the trained critic (backbone + value head) checkpoints
                # beside the actor — force, since the actor's save already
                # consumed this step's frequency trigger
                saver.save(critic, epoch, epoch_step, global_step,
                           name="critic", force=True, tokenizer=tokenizer)
            if checkpointer.freq.check(epoch, global_step):
                recover.dump(actor, step_info, **dump_kwargs)

        actor.flush_stats()
        reward_mean = float(np.mean(batch["rewards"])) if "rewards" in batch else 0.0
        stats.scalar(reward=reward_mean, n_seqs=len(batch.get("rewards", [])))
        stats_logger.commit(
            epoch, epoch_step, global_step,
            [stats.export()] + train_stats + critic_stats,
        )
        logger.info(
            f"Epoch {epoch + 1}/{config.total_train_epochs} "
            f"Step {epoch_step + 1}/{steps_per_epoch} "
            f"(global {global_step + 1}/{total_steps}) done. "
            f"reward={reward_mean:.3f}"
        )

        if guard.requested:
            # preemption announced: the just-completed step is the dump
            # point — the relaunch loses zero steps
            preempt_exit(
                recover, actor, step_info,
                rollout_engines=(rollout,),
                dump_kwargs=dump_kwargs,
            )

    rollout.destroy()
    stats_logger.close()


if __name__ == "__main__":
    main(sys.argv[1:])
