"""Checkpoint/resume for interrupted experiments.

Behavioral counterpart of the reference's `RecoverHandler`
(areal/utils/recover.py:139): dump = engine checkpoint with optimizer state
+ dataloader position + saver/evaluator/stats-logger state + RecoverInfo;
load = restore all of it and replay the weight upload to (fresh) inference
servers; `check_if_recover` (:373) decides whether a run should resume.
"""

import json
import os
import pickle
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Optional

from areal_tpu.api.config import RecoverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo, WeightUpdateMeta
from areal_tpu.utils import logging

logger = logging.getLogger("recover")


@dataclass
class RecoverInfo:
    """(reference: recover.py RecoverInfo:29)"""

    recover_start: StepInfo
    last_step_info: StepInfo
    saver_info: Dict[str, Any] = field(default_factory=dict)
    checkpointer_info: Dict[str, Any] = field(default_factory=dict)
    evaluator_info: Dict[str, Any] = field(default_factory=dict)
    stats_logger_info: Dict[str, Any] = field(default_factory=dict)
    dataloader_info: Dict[str, Any] = field(default_factory=dict)
    hash_vals_to_ignore: list = field(default_factory=list)


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec

    def recover_root(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "recover",
        )

    def _info_path(self) -> str:
        return os.path.join(self.recover_root(), "recover_info.pkl")

    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        stats_logger=None,
        dataloader=None,
        tokenizer=None,
        extra_engines=None,  # {"critic": engine, ...} — saved beside the main one
    ) -> str:
        root = self.recover_root()
        ckpt = os.path.join(root, "checkpoint")
        os.makedirs(ckpt, exist_ok=True)
        engine.save(SaveLoadMeta(path=ckpt, with_optim=True, tokenizer=tokenizer))
        for name, eng in (extra_engines or {}).items():
            sub = os.path.join(root, f"checkpoint_{name}")
            os.makedirs(sub, exist_ok=True)
            eng.save(SaveLoadMeta(path=sub, with_optim=True, tokenizer=tokenizer))
        info = RecoverInfo(
            recover_start=StepInfo(
                epoch=step_info.epoch,
                epoch_step=step_info.epoch_step + 1,
                global_step=step_info.global_step + 1,
                steps_per_epoch=step_info.steps_per_epoch,
            ),
            last_step_info=step_info,
            saver_info=saver.state_dict() if saver else {},
            evaluator_info=evaluator.state_dict() if evaluator else {},
            stats_logger_info=stats_logger.state_dict() if stats_logger else {},
            dataloader_info=dataloader.state_dict() if dataloader else {},
        )
        with open(self._info_path(), "wb") as f:
            pickle.dump(info, f)
        with open(os.path.join(root, "recover_info.json"), "w") as f:
            json.dump(
                {"last_step_info": asdict(info.last_step_info)}, f
            )
        logger.info(f"dumped recover checkpoint @ step {step_info.global_step}")
        return root

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        stats_logger=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta: Optional[WeightUpdateMeta] = None,
        extra_engines=None,  # same mapping as dump(); loaded when present
    ) -> Optional[RecoverInfo]:
        """Restore everything; if an inference engine is given, replay the
        weight upload so fresh servers serve the recovered policy."""
        path = self._info_path()
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            info: RecoverInfo = pickle.load(f)
        ckpt = os.path.join(self.recover_root(), "checkpoint")
        engine.load(SaveLoadMeta(path=ckpt, with_optim=True))
        for name, eng in (extra_engines or {}).items():
            sub = os.path.join(self.recover_root(), f"checkpoint_{name}")
            if os.path.isdir(sub):
                eng.load(SaveLoadMeta(path=sub, with_optim=True))
            else:
                logger.warning(
                    "recover checkpoint has no %s engine state (%s); it "
                    "resumes from its initial weights", name, sub,
                )
        if saver is not None and info.saver_info:
            saver.load_state_dict(info.saver_info)
        if evaluator is not None and info.evaluator_info:
            evaluator.load_state_dict(info.evaluator_info)
        if stats_logger is not None and info.stats_logger_info:
            stats_logger.load_state_dict(info.stats_logger_info)
        if dataloader is not None and info.dataloader_info:
            dataloader.load_state_dict(info.dataloader_info)
        version = info.last_step_info.global_step + 1
        engine.set_version(version)
        if inference_engine is not None and weight_update_meta is not None:
            engine.update_weights(weight_update_meta)
            inference_engine.update_weights(weight_update_meta)
            inference_engine.set_version(version)
        logger.info(
            f"recovered from step {info.last_step_info.global_step}; "
            f"resuming at {info.recover_start.global_step}"
        )
        return info


def check_if_recover(config: RecoverConfig, run_id: int = 0) -> bool:
    """Should this launch resume from a recover checkpoint?
    (reference: recover.py:373)"""
    if config.mode == "disabled":
        return False
    info_path = os.path.join(
        config.fileroot, config.experiment_name, config.trial_name,
        "recover", "recover_info.pkl",
    )
    exists = os.path.exists(info_path)
    if config.mode == "resume":
        return exists
    if config.mode == "auto":
        return exists
    if config.mode == "fault":
        # only recover on relaunch (run_id > 0), not on a fresh submit
        return exists and run_id > 0
    return False
