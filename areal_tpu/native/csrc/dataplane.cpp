// Native data-plane kernels for the host-side hot path.
//
// TPU-native counterpart of the reference's csrc/ extensions: the reference
// ships CUDA interval-copy kernels (csrc/interval_op/interval_op.cu) for
// gathering/scattering parameter fragments and does its micro-batch
// bin-packing in Python (areal/utils/datapack.py ffd_allocate).  On TPU the
// device-side work belongs to XLA; what remains hot on the HOST is
//   (a) per-batch bin-packing (FFD / LPT) that runs in the rollout->train
//       handoff for every batch, and
//   (b) interval slice/set memcpy used when chunking parameter bytes for
//       the transfer weight-sync path.
// Compiled with g++ -O3 -shared -fPIC, loaded via ctypes
// (areal_tpu/native/__init__.py); every entry point has a pure-Python
// fallback with identical semantics (parity-tested).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// First-fit-decreasing bin packing.  Items sorted by decreasing size
// (stable: ties keep index order) are placed into the first existing bin
// with room, else a new bin.  Returns the bin count; bin_of[i] = bin of
// item i.  Items larger than capacity get singleton bins (first-fit finds
// no room, matching the Python reference semantics).
int64_t ffd_assign(const int64_t* sizes, int64_t n, int64_t capacity,
                   int32_t* bin_of) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return sizes[a] > sizes[b]; });
  std::vector<int64_t> loads;
  loads.reserve(64);
  for (int64_t k = 0; k < n; ++k) {
    const int64_t idx = order[k];
    const int64_t size = sizes[idx];
    int64_t placed = -1;
    for (size_t b = 0; b < loads.size(); ++b) {
      if (loads[b] + size <= capacity) {
        placed = static_cast<int64_t>(b);
        break;
      }
    }
    if (placed < 0) {
      placed = static_cast<int64_t>(loads.size());
      loads.push_back(0);
    }
    loads[placed] += size;
    bin_of[idx] = static_cast<int32_t>(placed);
  }
  return static_cast<int64_t>(loads.size());
}

// Longest-processing-time balanced partition into exactly k groups:
// descending sizes, each item to the currently lightest group (ties ->
// lowest group index, matching numpy argmin).
void lpt_assign(const int64_t* sizes, int64_t n, int64_t k,
                int32_t* group_of) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return sizes[a] > sizes[b]; });
  std::vector<int64_t> loads(k, 0);
  for (int64_t t = 0; t < n; ++t) {
    const int64_t idx = order[t];
    int64_t best = 0;
    for (int64_t g = 1; g < k; ++g) {
      if (loads[g] < loads[best]) best = g;
    }
    loads[best] += sizes[idx];
    group_of[idx] = static_cast<int32_t>(best);
  }
}

// Gather byte intervals [src + offsets[i], +lens[i]) into contiguous dst.
// (reference: csrc/interval_op slice_intervals, host flavor)
void slice_intervals(const uint8_t* src, const int64_t* offsets,
                     const int64_t* lens, int64_t n, uint8_t* dst) {
  int64_t out = 0;
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(dst + out, src + offsets[i], static_cast<size_t>(lens[i]));
    out += lens[i];
  }
}

// Scatter contiguous src back into byte intervals of dst.
// (reference: csrc/interval_op set_intervals, host flavor)
void set_intervals(uint8_t* dst, const int64_t* offsets, const int64_t* lens,
                   int64_t n, const uint8_t* src) {
  int64_t in = 0;
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(dst + offsets[i], src + in, static_cast<size_t>(lens[i]));
    in += lens[i];
  }
}

}  // extern "C"
