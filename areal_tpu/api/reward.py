"""Reward computation off the event loop.

Behavioral counterpart of the reference's `AsyncRewardWrapper`
(areal/api/reward_api.py:37): reward functions (sympy math verification,
sandboxed code execution) are CPU-heavy and must not block the rollout event
loop, so they run in a shared ProcessPoolExecutor with timeout, retry, and
automatic pool reconstruction when a worker dies.
"""

import asyncio
import multiprocessing
import threading
from concurrent.futures import BrokenExecutor, ProcessPoolExecutor
from typing import Callable, Optional

from areal_tpu.utils import logging

logger = logging.getLogger("reward")

REWARD_TIMEOUT_SECONDS = 15.0
_MAX_WORKERS = 4

_pool_lock = threading.Lock()
_pool: Optional[ProcessPoolExecutor] = None


def _new_pool() -> ProcessPoolExecutor:
    # spawn, not fork: the parent runs JAX (multithreaded) and an asyncio
    # loop; forking either risks deadlock
    return ProcessPoolExecutor(
        max_workers=_MAX_WORKERS, mp_context=multiprocessing.get_context("spawn")
    )


def _get_pool() -> ProcessPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = _new_pool()
            _warm_async(_pool)
        return _pool


def _warm_async(pool: ProcessPoolExecutor) -> None:
    """Kick one noop per worker and flip _pool_warm only when ALL complete:
    warmth is per-worker — a single fast reward on worker 1 proves nothing
    about worker 3 still importing jax.  The callback re-checks that `pool`
    is still the CURRENT pool (ADVICE r3): in-flight noops from a pool
    replaced by _recreate_pool must not mark the cold replacement warm."""
    remaining = [_MAX_WORKERS]
    lock = threading.Lock()

    def _done(fut):
        global _pool_warm
        if fut.cancelled() or fut.exception() is not None:
            return  # a dead pool's noop proves nothing
        with lock:
            remaining[0] -= 1
            if remaining[0] == 0:
                with _pool_lock:
                    if _pool is pool:
                        _pool_warm = True

    try:
        for _ in range(_MAX_WORKERS):
            pool.submit(_noop).add_done_callback(_done)
    except Exception:  # noqa: BLE001 — pool may be shutting down
        pass


def _noop() -> int:
    return 0


# flips once a pool task has completed: before that, per-call timeouts get
# a bootstrap allowance (spawn workers re-import the reward fn's module —
# often pulling in jax — which can exceed the steady-state reward timeout
# and silently zero the first batch's rewards)
_pool_warm = False
BOOTSTRAP_TIMEOUT_SECONDS = 120.0


def prewarm_reward_pool(timeout: float = 120.0) -> None:
    """Spin up the spawn workers ahead of the first real reward call."""
    global _pool_warm
    pool = _get_pool()
    futs = [pool.submit(_noop) for _ in range(_MAX_WORKERS)]
    for f in futs:
        f.result(timeout=timeout)
    _pool_warm = True


def _recreate_pool():
    global _pool, _pool_warm
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown(wait=False, cancel_futures=True)
        _pool = _new_pool()
        _pool_warm = False
        pool = _pool
    # warm-kick the replacement OUTSIDE the lock (ADVICE r3: without it
    # _pool_warm stays False forever and every timeout after a pool break
    # is inflated to the 120s bootstrap allowance)
    _warm_async(pool)
    return pool


class AsyncRewardWrapper:
    """Wraps a sync `reward_fn(...) -> float` as `await wrapper(...)`."""

    def __init__(
        self,
        reward_fn: Callable[..., float],
        timeout: float = REWARD_TIMEOUT_SECONDS,
        max_retries: int = 2,
    ):
        self.reward_fn = reward_fn
        self.timeout = timeout
        self.max_retries = max_retries

    async def __call__(self, *args, **kwargs) -> float:
        loop = asyncio.get_running_loop()
        for attempt in range(self.max_retries):
            pool = _get_pool()
            # cold pool: allow for spawn-worker bootstrap on the first call
            timeout = (
                self.timeout
                if _pool_warm
                else max(self.timeout, BOOTSTRAP_TIMEOUT_SECONDS)
            )
            try:
                fut = pool.submit(self.reward_fn, *args, **kwargs)
                return float(
                    await asyncio.wait_for(
                        asyncio.wrap_future(fut, loop=loop), timeout=timeout
                    )
                )
            except asyncio.TimeoutError:
                # Do NOT retry a timeout: a running pool task cannot be
                # cancelled, so resubmitting would occupy a second worker and
                # a few hung reward fns would clog the whole pool
                # (reference behavior: reward_api.py returns 0 on timeout).
                fut.cancel()
                logger.warning(
                    f"reward fn timed out after {timeout}s; returning 0"
                )
                return 0.0
            except BrokenExecutor:
                logger.warning("reward process pool broke; recreating")
                _recreate_pool()
            except Exception as e:  # noqa: BLE001 — a bad reward is reward 0
                logger.warning(f"reward fn raised {e!r}; returning 0")
                return 0.0
        return 0.0


def shutdown_reward_pool():
    global _pool
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown(wait=False, cancel_futures=True)
            _pool = None
