"""Experiment presets + automatic device-allocation heuristics.

Behavioral counterpart of the reference's experiment-preset layer
(realhf/experiments/common/common.py:627 auto device-mesh assignment,
realhf/api/quickstart/device_mesh.py:274 heuristic allocation): given a
model size and a chip budget, pick a sensible allocation expression and a
ready-to-edit config, so users start from `preset("gsm8k-grpo-1.5b")`
instead of a blank YAML.

The heuristics encode the TPU sizing rules the rest of the stack assumes:

- **tp** is chosen so one model replica's train state fits a chip's HBM
  (bf16 params + grads + AdamW moments ~ 8 bytes/param, plus ~25%
  activation headroom under remat);
- **fsdp** absorbs the remaining train chips (GSPMD ZeRO-3 over the fsdp
  axis is the default scale-out, mirroring the reference's FSDP engine);
- generation gets the larger chip share (async RL is generation-bound —
  the reference's benchmark splits ~3:1 gen:train);
- generation servers shard tp only as far as KV-cache+weights demand
  (serving needs ~2 bytes/param + KV, far less than training).
"""

import dataclasses
import math
from typing import Dict, Optional

from areal_tpu.api.alloc import AllocationMode

# per-chip usable HBM bytes (after runtime reserves), keyed by device kind
# prefix; the v5e figure matches the one real chip this repo benches on
HBM_BYTES = {
    "TPU v5 lite": 14 * 1024**3,
    "TPU v5p": 90 * 1024**3,
    "TPU v4": 28 * 1024**3,
    "default": 14 * 1024**3,
}

TRAIN_BYTES_PER_PARAM = 8.0 * 1.25  # bf16 p+g + f32 moments, remat headroom
GEN_BYTES_PER_PARAM = 2.0 * 1.5  # bf16 weights + KV/activation headroom


def _pow2_at_least(x: float, cap: int) -> int:
    p = 1
    while p < x and p < cap:
        p *= 2
    return p


def auto_allocation(
    n_devices: int,
    n_params: float,
    gen_fraction: float = 0.75,
    hbm_bytes: Optional[int] = None,
    device_kind: str = "default",
) -> str:
    """Pick a disaggregated allocation expression for an async-RL run.

    Returns e.g. "jax:d6t2+jax:d1f2t2" — gen servers on the left of '+',
    trainer mesh on the right (api/alloc.py dialect)."""
    if n_devices < 2:
        raise ValueError("async RL needs >= 2 chips (gen + train)")
    hbm = hbm_bytes or HBM_BYTES.get(device_kind, HBM_BYTES["default"])

    train_tp = _pow2_at_least(n_params * TRAIN_BYTES_PER_PARAM / hbm, n_devices)
    gen_tp = _pow2_at_least(n_params * GEN_BYTES_PER_PARAM / hbm, n_devices)

    n_gen = max(gen_tp, int(n_devices * gen_fraction) // gen_tp * gen_tp)
    n_train = n_devices - n_gen
    if n_train < train_tp:
        # shrink the gen share until one training replica fits
        while n_train < train_tp and n_gen - gen_tp >= gen_tp:
            n_gen -= gen_tp
            n_train = n_devices - n_gen
        if n_train < train_tp:
            raise ValueError(
                f"{n_devices} chips cannot host train tp={train_tp} "
                f"plus a gen server (model {n_params / 1e9:.1f}B)"
            )
    gen_dp = n_gen // gen_tp
    fsdp = n_train // train_tp
    gen = f"jax:d{gen_dp}" + (f"t{gen_tp}" if gen_tp > 1 else "")
    train = f"jax:f{fsdp}" + (f"t{train_tp}" if train_tp > 1 else "")
    expr = f"{gen}+{train}"
    AllocationMode.from_str(expr)  # validate against the real parser
    return expr


# ---------------------------------------------------------------------------
# Named experiment presets
# ---------------------------------------------------------------------------


def _gsm8k_grpo(model_path: str, n_params: float, n_devices: int) -> Dict:
    """Config-dict preset mirroring examples/math/gsm8k_grpo.py + the
    reference's example YAMLs (examples/math/gsm8k_grpo.yaml)."""
    return {
        "experiment_name": "gsm8k-grpo",
        "trial_name": "trial0",
        "allocation_mode": auto_allocation(n_devices, n_params),
        "train_dataset": {
            "path": "openai/gsm8k",
            "type": "gsm8k",
            "batch_size": 8,
            "shuffle": True,
        },
        "actor": {
            "experiment_name": "gsm8k-grpo",
            "trial_name": "trial0",
            "path": model_path,
            "dtype": "bfloat16",
            "group_size": 8,
            "group_reward_norm": True,
            "use_decoupled_loss": True,
            "recompute_logprob": True,
            "ppo_n_minibatches": 2,
            "optimizer": {"lr": 1e-6, "lr_scheduler_type": "constant"},
        },
        "gconfig": {
            "max_new_tokens": 1024,
            "temperature": 1.0,
            "n_samples": 8,
        },
        "rollout": {
            "experiment_name": "gsm8k-grpo",
            "trial_name": "trial0",
            "max_concurrent_rollouts": 64,
            "max_head_offpolicyness": 4,
        },
        "gen_server": {"model_path": model_path, "max_context_len": 2048},
    }


_PRESETS = {
    "gsm8k-grpo-tiny": lambda: _gsm8k_grpo("", 5e6, 2),
    "gsm8k-grpo-1.5b": lambda: _gsm8k_grpo("Qwen/Qwen2.5-1.5B-Instruct", 1.54e9, 8),
    "gsm8k-grpo-7b": lambda: _gsm8k_grpo("Qwen/Qwen2.5-7B-Instruct", 7.6e9, 32),
}


def preset(name: str) -> Dict:
    """A ready-to-edit config dict (feed to load_expr_config via YAML dump,
    or use as overrides)."""
    if name not in _PRESETS:
        raise ValueError(f"unknown preset {name!r}; known: {sorted(_PRESETS)}")
    return _PRESETS[name]()


def list_presets():
    return sorted(_PRESETS)
